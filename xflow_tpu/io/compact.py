"""Host-side batch compaction: the CompactBatch form and the dictionary
wire format (Config.wire_dedup).

BENCH_r05 measured the packed pipeline link-bound at ~130 wire
bytes/example while compute sustained 6x more examples/sec — the classic
terabyte-scale-trainer gap, and the classic fix: compress and
deduplicate the sparse traffic on the host BEFORE it crosses the link
(arXiv:2201.05500), exploiting the zipf skew instead of shipping raw
(key, val) pairs (Parallax, arXiv:1808.02621).  The host is idle
relative to the link, so the work is free where it runs.

``compact_batch`` (CompactBatch.from_batch) deduplicates a padded
Batch's keys — the kernel half (native ``xf_dict_encode`` with a numpy
fallback, ``dedup_select``) emits the batch's unique keys (u64) and a
per-element u32 index into the unique list — and re-encodes every plane
by where its information actually lives:

* **cold keys, two tiers.**  A per-batch DICTIONARY of the (at most)
  2^16 most-duplicated keys ships once as u24/u32 values; their
  occurrences ship as u16 indices into it.  The near-unique zipf TAIL
  ships as raw u24/u32 values — measured on the zipf-cache workload the
  dictionary covers ~57% of cold occurrences with ~53k entries, so
  dictionary-tier occurrences cost 2 bytes instead of 4 AND the device
  scatter for them collapses to U unique rows (parallel/step.py
  consumes the indices directly; ops/sparse.py::consolidate_indexed).
  A full dictionary would LOSE bytes here: at the measured 2.9x cold
  duplication, unique keys are ~35% of occurrences and shipping them
  all costs more than the index plane saves.  Dedup where the
  duplication lives; ship the tail raw.
* **hot keys, two tiers.**  Post-remap hot row ids are frequency
  ranks < H; ids < 256 (~61% of hot occurrences at the flagship remap)
  ship as u8, the rest as packed u12 (H <= 2^12) or u16.
* **padding never ships.**  Real entries stream flat in row-major
  order with per-row u8 counts; [B, K] geometry is rebuilt on device.
* **labels/weights ship as bitmaps** (eligibility requires the 0/1
  hash-mode invariant, like the plain compact wire).

Plane capacities are rounded up to a coarse granule (plane_cap) so a
steady stream of same-geometry batches maps to ONE set of array shapes
— one XLA compile, ``compile_count`` flat — while per-batch content
still sets the bytes that actually cross the link.

At the bench flagship geometry this lands at ~70 wire bytes/example
vs 130 for the plain compact wire (docs/PERF.md "Wire format and
compaction").  The same planes are the packed-cache v2 record format
(io/packed.py), so steady-state epochs read pre-compacted records and
pay ZERO per-batch compaction work.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from xflow_tpu.io.batch import Batch

# Dictionary capacity: u16 occurrence indices, and a [DICT_CAP, D]
# consolidation buffer small enough to live in cache (CPU) / VMEM-near
# working set (TPU).
DICT_CAP = 65536
# Tail/dict key width: 3 bytes holds any key < 2^24 (the flagship
# table); larger tables use 4.
_TAIL_CODE = np.uint32(0xFFFFFFFF)  # dedup_select: "not in dictionary"

GRANULE_DIV = 32
GRANULE_MIN = 256


def plane_cap(
    n: int, slots: int, div: int = GRANULE_DIV, mn: int = GRANULE_MIN
) -> int:
    """Static-shape capacity for a flat plane holding ``n`` real
    entries out of at most ``slots``: round up to a coarse granule so
    same-geometry batches share one capacity (one compiled program),
    never exceeding ``slots``."""
    if n <= 0:
        return 0
    g = max(mn, slots // div)
    return min(-(-n // g) * g, slots)


def dedup_select(
    keys: np.ndarray, dict_cap: int = DICT_CAP
) -> tuple[np.ndarray, np.ndarray]:
    """The compaction kernel: deduplicate a flat u64 key array into
    (unique_keys[u64], per-element u32 codes).  A code is the element's
    index into the unique list, or 0xFFFFFFFF when its key fell outside
    the dictionary — the dictionary holds the most-duplicated keys,
    capped at ``dict_cap`` entries by an occurrence-count threshold
    (the smallest t with |{count >= t}| <= dict_cap, so the selected
    SET is deterministic and the native kernel reproduces it exactly;
    only the within-dictionary order may differ).

    Native C (xflow_tpu/native: xf_dict_encode, hash-table two-pass)
    when built, else the numpy path below — parity enforced by
    tests/test_compact.py.
    """
    keys = np.ascontiguousarray(keys, dtype=np.int64)
    n = len(keys)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.uint32)
    from xflow_tpu import native

    if native.available() and native.has_dict_encode():
        return native.native_dict_encode(keys, dict_cap)
    uniq, inv, cnt = np.unique(
        keys, return_inverse=True, return_counts=True
    )
    if len(uniq) <= dict_cap:
        return uniq, inv.astype(np.uint32)
    # histogram of counts (clamped) -> smallest threshold that fits
    hist = np.bincount(np.minimum(cnt, dict_cap + 1))
    ge = np.cumsum(hist[::-1])[::-1]  # ge[t] = #keys with count >= t
    t = 1
    while t < len(ge) and ge[t] > dict_cap:
        t += 1
    sel = cnt >= t
    if int(sel.sum()) > dict_cap:
        # only reachable when > dict_cap keys EACH repeat > dict_cap
        # times (counts clamp into the histogram's last bucket) —
        # beyond any real batch at the default cap, but dict_cap is a
        # public parameter: truncate deterministically rather than
        # overflow the capped planes (the native kernel's nd guard)
        keep = np.flatnonzero(sel)[:dict_cap]
        sel = np.zeros(len(uniq), bool)
        sel[keep] = True
    slot = np.full(len(uniq), _TAIL_CODE, np.uint32)
    slot[sel] = np.arange(int(sel.sum()), dtype=np.uint32)
    return uniq[sel], slot[inv]


def _pack_keys(keys: np.ndarray, key_bytes: int, cap: int) -> np.ndarray:
    """Little-endian u24 ([cap, 3] u8) or u32 ([cap]) key plane."""
    n = len(keys)
    if key_bytes == 4:
        out = np.zeros(cap, np.uint32)
        out[:n] = keys.astype(np.uint32)
        return out
    k = keys.astype(np.uint32)
    out = np.zeros((cap, 3), np.uint8)
    out[:n, 0] = k & 0xFF
    out[:n, 1] = (k >> 8) & 0xFF
    out[:n, 2] = (k >> 16) & 0xFF
    return out


def _unpack_keys(plane: np.ndarray, n: int) -> np.ndarray:
    if plane.dtype == np.uint32:
        return plane[:n].astype(np.int64)
    p = plane[:n].astype(np.int64)
    return p[:, 0] | (p[:, 1] << 8) | (p[:, 2] << 16)


def _pack_nibbles(vals: np.ndarray, cap_pairs: int) -> np.ndarray:
    """Pack 4-bit values (even index -> low nibble) into u8."""
    n = len(vals)
    full = np.zeros(cap_pairs * 2, np.uint8)
    full[:n] = vals.astype(np.uint8)
    return full[0::2] | (full[1::2] << 4)


def _pack_bits(bits: np.ndarray) -> np.ndarray:
    return np.packbits(bits.astype(bool), bitorder="little")


def _unpack_bits(plane: np.ndarray, n: int) -> np.ndarray:
    return np.unpackbits(plane, count=n, bitorder="little")


_SLOT_DTYPES = (np.uint8, np.uint16, np.int32)


def _slots_code(slots: np.ndarray) -> int:
    """0/1/2 -> u8/u16/i32: the narrowest dtype holding every value."""
    if len(slots) == 0 or 0 <= slots.min() and slots.max() <= 0xFF:
        return 0
    if 0 <= slots.min() and slots.max() <= 0xFFFF:
        return 1
    return 2


def _flat_plane(vals: np.ndarray, cap: int, dtype) -> np.ndarray:
    out = np.zeros(cap, dtype)
    out[: len(vals)] = vals
    return out


@dataclasses.dataclass
class CompactBatch:
    """A Batch with padding stripped, keys deduplicated, and every
    plane at its wire width.  ``wire()`` is (modulo the slots clamp) a
    plane collection — no per-batch work — which is why packed-cache v2
    records store exactly this form.

    Geometry (batch_size/max_nnz/hot_nnz/num_real) mirrors Batch so
    trainer bookkeeping handles either form."""

    # geometry / totals
    batch_size: int
    cold_nnz: int   # Kc — Batch.max_nnz
    hot_nnz_cap: int  # Kh
    table_size: int
    hot_size: int
    n_real: int
    n_cold: int
    n_dict: int      # real dictionary entries (<= DICT_CAP)
    n_dict_occ: int  # cold occurrences coded as dictionary indices
    n_hot: int
    n_h8: int        # hot occurrences with id < 256
    key_bytes: int   # 3 (u24) or 4 (u32)
    hx16: bool       # hot large tier is u16 (hot_size > 2^12)
    slots_code: int  # 0/1/2 -> u8/u16/i32 slot planes (exact, unclamped)
    # planes (all numpy, capacities from plane_cap)
    cu: np.ndarray   # [capD, 3] u8 | [capD] u32 — dictionary keys
    ci: np.ndarray   # [capI] u16 — dict-tier occurrence indices
    ct: np.ndarray   # [capT, 3] u8 | [capT] u32 — tail-tier keys
    cf: np.ndarray   # [ceil(capC/8)] u8 — per-cold-entry tier bitmap (1=dict)
    cc: np.ndarray   # [B] u8 — per-row cold counts
    h8: np.ndarray   # [cap8] u8 — hot ids < 256
    hx: np.ndarray   # [capX] u8 low bytes | [capX] u16
    hxh: np.ndarray  # [ceil(capX/2)] u8 high nibbles ([] when hx16)
    hf: np.ndarray   # [ceil(capH/8)] u8 — per-hot-entry tier bitmap (1=u8)
    hc: np.ndarray   # [B] u8 — per-row hot counts
    lb: np.ndarray   # [ceil(B/8)] u8 — labels bitmap
    wb: np.ndarray   # [ceil(B/8)] u8 — weights bitmap
    cs: np.ndarray   # [capC] slots (cold, flat row-major; exact dtype)
    hs: np.ndarray   # [capH] slots (hot, flat row-major)

    # -- Batch-compatible surface ------------------------------------------

    @property
    def max_nnz(self) -> int:
        return self.cold_nnz

    @property
    def hot_nnz(self) -> int:
        return self.hot_nnz_cap

    def num_real(self) -> int:
        return self.n_real

    @property
    def cold_touched(self) -> int:
        """Big-table rows the cold section touches after host dedup:
        dictionary entries plus raw tail occurrences — the ONE
        definition behind compaction_ratio (= n_cold / cold_touched)
        in the bench, the ``wire`` metrics row, and PERF.md."""
        return self.n_dict + (self.n_cold - self.n_dict_occ)

    @property
    def labels(self) -> np.ndarray:
        return _unpack_bits(self.lb, self.batch_size).astype(np.float32)

    @property
    def weights(self) -> np.ndarray:
        return _unpack_bits(self.wb, self.batch_size).astype(np.float32)

    # -- construction ------------------------------------------------------

    @classmethod
    def from_batch(
        cls,
        batch: Batch,
        table_size: int,
        hot_size: int,
        dict_cap: int = DICT_CAP,
        check: bool = True,
        strict_layout: bool = False,
    ) -> "CompactBatch":
        """Compact one padded Batch.  Only valid for hash-mode batches
        (binary vals, 0/1 labels/weights) with per-row counts <= 255 —
        everything the loaders produce; callers with heterogeneous
        traffic keep ``check=True`` (the serving engine opts out of
        this wire entirely).  ``strict_layout`` additionally enforces
        the packed-v2 byte-exact contract (see _validate)."""
        if check:
            _validate(batch, table_size, hot_size, strict_layout)
        b, kc = batch.keys.shape
        kh = batch.hot_keys.shape[1]
        cm = batch.mask > 0
        hm = batch.hot_mask > 0
        cc = cm.sum(axis=1).astype(np.uint8)
        hc = hm.sum(axis=1).astype(np.uint8)
        ckeys = batch.keys[cm].astype(np.int64)
        cslots = batch.slots[cm]
        hkeys = batch.hot_keys[hm]
        hslots = batch.hot_slots[hm]
        n_cold, n_hot = len(ckeys), len(hkeys)
        key_bytes = 3 if table_size <= 1 << 24 else 4
        hx16 = hot_size > 1 << 12

        dict_keys, codes = dedup_select(ckeys, dict_cap)
        nd = len(dict_keys)
        in_dict = codes != _TAIL_CODE
        n_dict_occ = int(in_dict.sum())
        slots_cap = b * kc
        cap_d = plane_cap(nd, min(dict_cap, slots_cap))
        cap_i = plane_cap(n_dict_occ, slots_cap)
        cap_t = plane_cap(n_cold - n_dict_occ, slots_cap)
        cap_c = plane_cap(n_cold, slots_cap)

        small = hkeys < 256
        n_h8 = int(small.sum())
        n_hx = n_hot - n_h8
        hslots_cap = b * kh
        cap_8 = plane_cap(n_h8, hslots_cap)
        cap_x = plane_cap(n_hx, hslots_cap)
        cap_h = plane_cap(n_hot, hslots_cap)

        hx_vals = hkeys[~small]
        if hx16:
            hx = _flat_plane(hx_vals, cap_x, np.uint16)
            hxh = np.zeros(0, np.uint8)
        else:
            hx = _flat_plane(hx_vals & 0xFF, cap_x, np.uint8)
            hxh = _pack_nibbles(hx_vals >> 8, (cap_x + 1) // 2)

        scode = max(_slots_code(cslots), _slots_code(hslots))
        sdtype = _SLOT_DTYPES[scode]
        cflags = np.zeros(cap_c, bool)
        cflags[:n_cold] = in_dict
        hflags = np.zeros(cap_h, bool)
        hflags[:n_hot] = small
        return cls(
            batch_size=b, cold_nnz=kc, hot_nnz_cap=kh,
            table_size=table_size, hot_size=hot_size,
            n_real=batch.num_real(), n_cold=n_cold, n_dict=nd,
            n_dict_occ=n_dict_occ, n_hot=n_hot, n_h8=n_h8,
            key_bytes=key_bytes, hx16=hx16, slots_code=scode,
            cu=_pack_keys(dict_keys, key_bytes, cap_d),
            ci=_flat_plane(codes[in_dict], cap_i, np.uint16),
            ct=_pack_keys(ckeys[~in_dict], key_bytes, cap_t),
            cf=_pack_bits(cflags),
            cc=cc,
            h8=_flat_plane(hkeys[small], cap_8, np.uint8),
            hx=hx, hxh=hxh,
            hf=_pack_bits(hflags),
            hc=hc,
            lb=_pack_bits(batch.labels),
            wb=_pack_bits(batch.weights),
            cs=_flat_plane(cslots, cap_c, sdtype),
            hs=_flat_plane(hslots, cap_h, sdtype),
        )

    # -- expansion (exact inverse for loader-produced batches) -------------

    def expand(self) -> Batch:
        """Reconstruct the padded Batch.  Byte-exact for any
        loader-produced batch (left-compacted rows): real-entry order
        is preserved through the flat streams, padding is zeros."""
        b, kc, kh = self.batch_size, self.cold_nnz, self.hot_nnz_cap
        cflags = _unpack_bits(self.cf, self.n_cold).astype(bool)
        keys_flat = np.zeros(self.n_cold, np.int64)
        dict_keys = _unpack_keys(self.cu, self.n_dict)
        if self.n_dict_occ:
            keys_flat[cflags] = dict_keys[
                self.ci[: self.n_dict_occ].astype(np.int64)
            ]
        if self.n_cold - self.n_dict_occ:
            keys_flat[~cflags] = _unpack_keys(
                self.ct, self.n_cold - self.n_dict_occ
            )
        hot_flat = self._hot_ids()

        def unflatten(flat, counts, width, dtype):
            out = np.zeros((b, width), dtype)
            valid = np.arange(width)[None, :] < counts[:, None]
            out[valid] = flat
            return out

        cc = self.cc.astype(np.int64)
        hc = self.hc.astype(np.int64)
        cm = (np.arange(kc)[None, :] < cc[:, None]).astype(np.float32)
        hm = (np.arange(kh)[None, :] < hc[:, None]).astype(np.float32)
        return Batch(
            keys=unflatten(keys_flat, cc, kc, np.int32),
            slots=unflatten(self.cs[: self.n_cold], cc, kc, np.int32),
            vals=cm.copy(),
            mask=cm,
            labels=self.labels,
            weights=self.weights,
            hot_keys=unflatten(hot_flat, hc, kh, np.int32),
            hot_slots=unflatten(self.hs[: self.n_hot], hc, kh, np.int32),
            hot_vals=hm.copy(),
            hot_mask=hm,
        )

    def _hot_ids(self) -> np.ndarray:
        """Flat hot-section row ids (original occurrence order)
        reconstructed from the tiered u8/u12/u16 planes — shared by
        expand() and touched_rows()."""
        hflags = _unpack_bits(self.hf, self.n_hot).astype(bool)
        hot_flat = np.zeros(self.n_hot, np.int64)
        hot_flat[hflags] = self.h8[: self.n_h8].astype(np.int64)
        n_hx = self.n_hot - self.n_h8
        if n_hx:
            if self.hx16:
                hot_flat[~hflags] = self.hx[:n_hx].astype(np.int64)
            else:
                hi = np.repeat(self.hxh, 2)[:n_hx].astype(np.int64)
                hi = np.where(
                    np.arange(n_hx) % 2 == 0, hi & 0xF, hi >> 4
                )
                hot_flat[~hflags] = self.hx[:n_hx].astype(np.int64) | (
                    hi << 8
                )
        return hot_flat

    def touched_rows(self) -> np.ndarray:
        """Big-table row ids this batch touches — cold dictionary keys,
        cold tail occurrences (may repeat), and hot-section ids (row
        ids in [0, hot_size) by construction).  The delta-export
        ledger's per-batch input (stream/delta.py): available straight
        off the compact planes, no expand() cost."""
        parts = [
            _unpack_keys(self.cu, self.n_dict),
            _unpack_keys(self.ct, self.n_cold - self.n_dict_occ),
        ]
        if self.n_hot:
            parts.append(self._hot_ids())
        return np.concatenate(parts)

    # -- wire --------------------------------------------------------------

    def wire(self, ship_slots: bool) -> dict[str, np.ndarray]:
        """The numpy planes that cross the link, keyed by the cw_*
        names parallel/step.py::_expand_dict_wire decodes.  Slots ship
        (clamped to the u8 ignored-range convention of
        compact_wire_np) only when the model reads them."""
        out = {
            "cw_cu": self.cu,
            "cw_cun": np.asarray([self.n_dict], np.int32),
            "cw_ci": self.ci,
            "cw_ct": self.ct,
            "cw_cf": self.cf,
            "cw_cc": self.cc,
            "cw_lb": self.lb,
            "cw_wb": self.wb,
        }
        if self.hot_nnz_cap:
            out.update({
                "cw_h8": self.h8, "cw_hx": self.hx, "cw_hxh": self.hxh,
                "cw_hf": self.hf, "cw_hc": self.hc,
            })
        if ship_slots:
            out["cw_cs"] = _clamp_slots_u8(self.cs)
            if self.hot_nnz_cap:
                out["cw_hs"] = _clamp_slots_u8(self.hs)
        return out

    def wire_nbytes(self, ship_slots: bool) -> int:
        return sum(v.nbytes for v in self.wire(ship_slots).values())


def _clamp_slots_u8(slots: np.ndarray) -> np.ndarray:
    """Slots to the u8 wire plane under the shared lossless-clamp rule
    (compact_wire_np): anything outside [0, 255] maps to 255, which
    every slot consumer already ignores for max_fields <= 255."""
    if slots.dtype == np.uint8:
        return slots
    s = slots.astype(np.int64)
    return np.where((s < 0) | (s > 255), 255, s).astype(np.uint8)


def _validate(
    batch: Batch,
    table_size: int,
    hot_size: int,
    strict_layout: bool = False,
) -> None:
    """Compaction invariants — the dict wire's eligibility contract:
    binary features, 0/1 labels/weights, in-range keys, rows no wider
    than the u8 count planes.  ``strict_layout`` additionally requires
    left-compacted rows (no interior mask holes): that is the packed-v2
    BYTE-EXACT round-trip contract (io/packed.py), loader batches
    satisfy it by construction, and without it compaction is still
    semantically lossless — entries re-compact leftward with their
    (key, slot, val) triplets intact, and every model reduces over the
    feature axis permutation-invariantly."""
    if not (
        np.array_equal(batch.vals * batch.mask, batch.mask)
        and np.array_equal(
            batch.hot_vals * batch.hot_mask, batch.hot_mask
        )
    ):
        raise ValueError(
            "compact_batch requires binary features (val 1 wherever "
            "mask 1); use wire_dedup='off' for value-carrying batches"
        )
    for arr in (batch.labels, batch.weights):
        if not np.isin(arr, (0.0, 1.0)).all():
            raise ValueError(
                "compact_batch requires 0/1 labels and weights; use "
                "wire_dedup='off'"
            )
    if batch.max_nnz > 255 or batch.hot_nnz > 255:
        raise ValueError(
            "compact_batch per-row counts are u8: max_nnz and hot_nnz "
            "must be <= 255"
        )
    cm = batch.mask > 0
    if strict_layout:
        cc = cm.sum(axis=1)
        hm_ = batch.hot_mask > 0
        hc = hm_.sum(axis=1)
        if not (
            np.array_equal(
                cm, np.arange(batch.max_nnz)[None, :] < cc[:, None]
            )
            and np.array_equal(
                hm_, np.arange(batch.hot_nnz)[None, :] < hc[:, None]
            )
        ):
            raise ValueError(
                "packed-v2 records require left-compacted rows "
                "(loader batches are; the byte-exact round-trip "
                "contract — user batches with mask holes still ride "
                "the dict wire, just not the cache)"
            )
    if len(batch.keys[cm]) and not (
        0 <= batch.keys[cm].min()
        and int(batch.keys[cm].max()) < table_size
    ):
        raise ValueError("compact_batch: cold key outside [0, table_size)")
    hm = batch.hot_mask > 0
    if len(batch.hot_keys[hm]) and not (
        0 <= batch.hot_keys[hm].min()
        and int(batch.hot_keys[hm].max()) < max(hot_size, 1)
    ):
        raise ValueError("compact_batch: hot key outside [0, hot_size)")


def plane_specs(
    *,
    batch_size: int,
    cold_nnz: int,
    hot_nnz_cap: int,
    key_bytes: int,
    hx16: bool,
    slots_code: int,
    n_cold: int,
    n_dict: int,
    n_dict_occ: int,
    n_hot: int,
    n_h8: int,
    dict_cap: int = DICT_CAP,
    granule_div: int = GRANULE_DIV,
    granule_min: int = GRANULE_MIN,
) -> list[tuple[str, tuple, np.dtype]]:
    """(field, shape, dtype) for every CompactBatch plane, in the
    packed-cache v2 record order (io/packed.py).  Deterministic from
    the record's counts and the shard header's wire parameters, so the
    writer's serialization and the reader's zero-copy views cannot
    drift."""
    b = batch_size

    def cap(n, slots):
        return plane_cap(n, slots, granule_div, granule_min)

    c_slots = b * cold_nnz
    cap_d = cap(n_dict, min(dict_cap, c_slots))
    cap_i = cap(n_dict_occ, c_slots)
    cap_t = cap(n_cold - n_dict_occ, c_slots)
    cap_c = cap(n_cold, c_slots)
    kshape = (lambda n: ((n, 3), np.dtype(np.uint8))) if key_bytes == 3 \
        else (lambda n: ((n,), np.dtype(np.uint32)))
    sdtype = np.dtype(_SLOT_DTYPES[slots_code])
    u8 = np.dtype(np.uint8)
    specs = [
        ("cu",) + kshape(cap_d),
        ("ci", (cap_i,), np.dtype(np.uint16)),
        ("ct",) + kshape(cap_t),
        ("cf", ((cap_c + 7) // 8,), u8),
        ("cc", (b,), u8),
    ]
    if hot_nnz_cap:
        h_slots = b * hot_nnz_cap
        cap_8 = cap(n_h8, h_slots)
        cap_x = cap(n_hot - n_h8, h_slots)
        cap_h = cap(n_hot, h_slots)
        specs += [
            ("h8", (cap_8,), u8),
            ("hx", (cap_x,), np.dtype(np.uint16) if hx16 else u8),
            ("hxh", (0 if hx16 else (cap_x + 1) // 2,), u8),
            ("hf", ((cap_h + 7) // 8,), u8),
            ("hc", (b,), u8),
        ]
    specs += [
        ("lb", ((b + 7) // 8,), u8),
        ("wb", ((b + 7) // 8,), u8),
        ("cs", (cap_c,), sdtype),
    ]
    if hot_nnz_cap:
        cap_h = cap(n_hot, b * hot_nnz_cap)
        specs += [("hs", (cap_h,), sdtype)]
    return specs


def from_planes(
    meta: dict, counts: dict, planes: dict[str, np.ndarray]
) -> CompactBatch:
    """Assemble a CompactBatch from reader-provided plane views (the
    packed-cache v2 record path).  ``meta`` holds the shard-level wire
    parameters, ``counts`` the per-record totals."""
    b = meta["batch_size"]
    kh = meta["hot_nnz"]
    zeros_u8 = np.zeros(0, np.uint8)
    return CompactBatch(
        batch_size=b,
        cold_nnz=meta["cold_nnz"],
        hot_nnz_cap=kh,
        table_size=meta["table_size"],
        hot_size=meta["hot_size"],
        n_real=counts["n_real"],
        n_cold=counts["n_cold"],
        n_dict=counts["n_dict"],
        n_dict_occ=counts["n_dict_occ"],
        n_hot=counts["n_hot"],
        n_h8=counts["n_h8"],
        key_bytes=meta["key_bytes"],
        hx16=meta["hx16"],
        slots_code=counts["slots_code"],
        cu=planes["cu"], ci=planes["ci"], ct=planes["ct"],
        cf=planes["cf"], cc=planes["cc"],
        h8=planes.get("h8", zeros_u8),
        hx=planes.get("hx", zeros_u8),
        hxh=planes.get("hxh", zeros_u8),
        hf=planes.get("hf", zeros_u8),
        hc=planes.get("hc", np.zeros(b, np.uint8)),
        lb=planes["lb"], wb=planes["wb"],
        cs=planes["cs"],
        hs=planes.get("hs", np.zeros(0, _SLOT_DTYPES[counts["slots_code"]])),
    )


def compact_batch(
    batch: Batch,
    table_size: int,
    hot_size: int,
    dict_cap: int = DICT_CAP,
    check: bool = True,
    strict_layout: bool = False,
) -> CompactBatch:
    """Functional alias for CompactBatch.from_batch (the name the
    native kernel, docs, and bench refer to)."""
    return CompactBatch.from_batch(
        batch, table_size, hot_size, dict_cap, check, strict_layout
    )
