"""Binary block cache: pre-parsed, pre-hashed CSR shards.

The reference's entire input path re-parses libffm TEXT every epoch
(load_data_from_disk.cc:103-210 runs tokenize+hash per block per pass);
that cost caps end-to-end throughput at the host's parse rate — ~100
MB/s/core here vs >1M examples/s device capacity (docs/PERF.md).  This
module decouples them: convert each text shard ONCE to a binary file of
raw CSR block arrays, and steady-state training streams those at memory
speed — no tokenizing, no hashing, no float parsing.

Format (little-endian, self-describing blocks):

    magic   8 bytes  b"XFBC0001"
    hlen    u32      header JSON length
    header  bytes    {"version": 1, "hash_mode": bool, "hash_seed": int,
                      "examples": int, "nnz": int, "blocks": int}
    then until EOF, one record per parsed text block:
      n_rows u64 | nnz u64
      labels  f32[n_rows]
      row_ptr i64[n_rows+1]
      keys    i64[nnz]   FULL keys: the 64-bit murmur hash
                         (two's-complement view) in hash mode, the raw
                         fid in numeric mode — NOT reduced mod
                         table_size, so one cache serves any table size
                         (reduction happens at load, bit-identical to
                         the text parser's)
      slots   i32[nnz]
      vals    f32[nnz]

A resume offset in a binary shard is the byte offset of a record start
(the first record's offset doubles as "start of data"), so the loader's
(batch, resume_offset) contract is unchanged between text and binary
shards — ShardLoader sniffs the magic and picks the block source.

Convert via the CLI:

    python -m xflow_tpu.io.binary --train PREFIX --out PREFIX.bin
                                  [--no-hash] [--seed N] [--block-mib N]
"""

from __future__ import annotations

import os
import struct
from typing import BinaryIO, Iterator

import numpy as np

from xflow_tpu.io import container
from xflow_tpu.io.batch import ParsedBlock

MAGIC = b"XFBC0001"
_REC_HDR = struct.Struct("<QQ")  # n_rows, nnz
# sanity ceiling on record header counts: a u64 count near 2^64 (bit
# rot / inflation attack) would push read sizes past sys.maxsize and
# crash with an untyped OverflowError instead of a typed refusal
# (found by analysis/wirefuzz.py)
_MAX_REC_COUNT = 1 << 48


def is_binary_shard(path: str) -> bool:
    return container.sniff(path, MAGIC)


def read_header(f: BinaryIO) -> tuple[dict, int]:
    """Returns (header dict, byte offset of the first record)."""
    return container.read_header(f, MAGIC, "binary shard")


def reduce_keys(raw: np.ndarray, table_size: int, hash_mode: bool) -> np.ndarray:
    """Reduce full stored keys mod table_size, bit-identical to
    libffm.parse_block's reduction: uint64 arithmetic for hashes,
    numpy int64 mod (sign of divisor) for numeric fids."""
    if hash_mode:
        return (raw.view(np.uint64) % np.uint64(table_size)).astype(np.int64)
    return raw % np.int64(table_size)


def _write_record(f: BinaryIO, block: ParsedBlock) -> None:
    n, nnz = block.num_samples, int(block.row_ptr[-1])
    f.write(_REC_HDR.pack(n, nnz))
    f.write(np.ascontiguousarray(block.labels, np.float32).tobytes())
    f.write(np.ascontiguousarray(block.row_ptr, np.int64).tobytes())
    f.write(np.ascontiguousarray(block.keys, np.int64).tobytes())
    f.write(np.ascontiguousarray(block.slots, np.int32).tobytes())
    f.write(np.ascontiguousarray(block.vals, np.float32).tobytes())


def _read_exact(f: BinaryIO, nbytes: int) -> bytes:
    buf = f.read(nbytes)
    if len(buf) != nbytes:
        raise ValueError(
            f"truncated binary shard: wanted {nbytes} bytes, got {len(buf)}"
        )
    return buf


def read_record(f: BinaryIO) -> ParsedBlock | None:
    """Read one record at the current offset; None at EOF."""
    hdr = f.read(_REC_HDR.size)
    if not hdr:
        return None
    if len(hdr) != _REC_HDR.size:
        raise ValueError("truncated binary shard record header")
    n, nnz = _REC_HDR.unpack(hdr)
    if n > _MAX_REC_COUNT or nnz > _MAX_REC_COUNT:
        raise ValueError(
            f"binary shard record header counts out of range "
            f"(n_rows={n} nnz={nnz}) — corrupt record"
        )
    labels = np.frombuffer(_read_exact(f, 4 * n), np.float32)
    row_ptr = np.frombuffer(_read_exact(f, 8 * (n + 1)), np.int64)
    keys = np.frombuffer(_read_exact(f, 8 * nnz), np.int64)
    slots = np.frombuffer(_read_exact(f, 4 * nnz), np.int32)
    vals = np.frombuffer(_read_exact(f, 4 * nnz), np.float32)
    return ParsedBlock(
        labels=labels, row_ptr=row_ptr, keys=keys, slots=slots, vals=vals
    )


def iter_blocks(
    f: BinaryIO,
    table_size: int,
    start_offset: int = 0,
    expect_hash_mode: bool | None = None,
    expect_hash_seed: int | None = None,
) -> Iterator[tuple[ParsedBlock, int, int]]:
    """Yield (block, offset, next_offset) records with keys reduced to
    [0, table_size) — the binary twin of the loader's text block
    source.  ``start_offset`` <= first-record-offset starts from the
    beginning; larger values must be a record boundary (a resume offset
    this iterator previously yielded)."""
    f.seek(0)
    meta, data_start = read_header(f)
    if expect_hash_mode is not None and bool(meta["hash_mode"]) != bool(
        expect_hash_mode
    ):
        raise ValueError(
            f"binary shard was converted with hash_mode="
            f"{meta['hash_mode']}, loader expects {expect_hash_mode}"
        )
    if (
        expect_hash_seed is not None
        and meta["hash_mode"]
        and int(meta["hash_seed"]) != int(expect_hash_seed)
    ):
        raise ValueError(
            f"binary shard was hashed with seed {meta['hash_seed']}, "
            f"loader expects {expect_hash_seed}"
        )
    offset = max(int(start_offset), data_start)
    if offset > data_start:
        # Records are variable-size, so validate the resume offset by
        # hopping record headers from the start (16 bytes read per
        # record — trivial at multi-MiB records).  A misaligned offset
        # (e.g. a cursor saved against the TEXT version of this shard)
        # would otherwise read garbage sizes; the packed format rejects
        # this with modulo arithmetic, this format by walking.
        pos = data_start
        while pos < offset:
            f.seek(pos)
            hdr = f.read(_REC_HDR.size)
            if len(hdr) != _REC_HDR.size:
                raise ValueError(
                    f"start_offset {start_offset} is past the shard end"
                )
            n, nnz = _REC_HDR.unpack(hdr)
            # labels f32[n] + row_ptr i64[n+1] + keys i64 + slots i32
            # + vals f32 (see _write_record)
            pos += _REC_HDR.size + 4 * n + 8 * (n + 1) + 16 * nnz
        if pos != offset:
            raise ValueError(
                f"start_offset {start_offset} is not a record boundary "
                "(cursor from a different file/format?)"
            )
    f.seek(offset)
    hash_mode = bool(meta["hash_mode"])
    while True:
        block = read_record(f)
        if block is None:
            return
        next_offset = f.tell()
        if len(block.keys):
            block.keys = reduce_keys(block.keys, table_size, hash_mode)
        yield block, offset, next_offset
        offset = next_offset


def shard_example_count(path: str) -> int:
    # metadata peek (header totals), not a streamed I/O boundary — the
    # record walk carries loader.read_block (xf: ignore[XF018])
    with open(path, "rb") as f:
        meta, _ = read_header(f)
        return int(meta["examples"])


def convert_shard(
    src: str,
    dst: str,
    hash_mode: bool = True,
    hash_seed: int = 0,
    block_mib: float = 8,
    parse_fn=None,
) -> dict:
    """Parse one libffm text shard and write the binary cache file
    (atomic: temp + rename).  Returns the header dict.  ``block_mib``
    sets the text-block granularity, which becomes the cache's resume
    granularity (same block-carry semantics as training on text,
    BlockReader)."""
    from xflow_tpu.io.libffm import BlockReader
    from xflow_tpu.io.loader import make_parse_fn

    if parse_fn is None:
        # table_size=0: store FULL keys (module docstring)
        parse_fn = make_parse_fn(0, hash_mode, hash_seed)
    examples = 0
    nnz = 0
    blocks = 0
    tmp = f"{dst}.tmp.{os.getpid()}"
    os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
    try:
        with open(src, "rb") as fin, open(tmp, "wb") as fout:
            meta = {
                "version": 1,
                "hash_mode": bool(hash_mode),
                "hash_seed": int(hash_seed),
            }
            hdr_len = container.write_placeholder_header(
                fout, MAGIC, meta, ("examples", "nnz", "blocks")
            )
            for raw in BlockReader(fin, max(1, int(block_mib * (1 << 20)))):
                block = parse_fn(raw)
                if block.num_samples == 0:
                    continue
                _write_record(fout, block)
                examples += block.num_samples
                nnz += int(block.row_ptr[-1])
                blocks += 1
            meta.update(examples=examples, nnz=nnz, blocks=blocks)
            container.rewrite_header(fout, MAGIC, meta, hdr_len)
        # offline conversion tool (CLI one-shot, atomic tmp+rename), not
        # the serving/training fault fabric (xf: ignore[XF018])
        os.replace(tmp, dst)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return meta


def convert_prefix(
    train_prefix: str,
    out_prefix: str,
    hash_mode: bool = True,
    hash_seed: int = 0,
    block_mib: float = 8,
    log=print,
) -> list[str]:
    """Convert every ``prefix-%05d`` shard (or a single file) to
    ``out_prefix-%05d`` binary shards; returns the output paths."""
    from xflow_tpu.trainer import find_shards

    outs = []
    for i, src in enumerate(find_shards(train_prefix)):
        dst = (
            f"{out_prefix}-{i:05d}"
            if src != train_prefix
            else out_prefix
        )
        meta = convert_shard(src, dst, hash_mode, hash_seed, block_mib)
        log(
            f"{src} -> {dst}: {meta['examples']} examples, "
            f"{meta['nnz']} nnz, {meta['blocks']} blocks"
        )
        outs.append(dst)
    return outs


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="xflow_tpu.io.binary",
        description="convert libffm text shards to the binary block cache",
    )
    p.add_argument("--train", required=True, help="text shard prefix")
    p.add_argument("--out", required=True, help="output shard prefix")
    p.add_argument("--no-hash", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--block-mib", type=float, default=8)
    a = p.parse_args(argv)
    convert_prefix(
        a.train, a.out, not a.no_hash, a.seed, a.block_mib
    )
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
