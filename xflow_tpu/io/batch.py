"""Padded static-shape minibatch representation.

The reference's minibatch is ``Data{fea_matrix: vector<vector<kv>>,
label: vector<int>}`` with ``kv = {fgid, fid, val}`` (io.h:18-22,61-65) —
ragged rows of sparse features.  XLA wants static shapes, so a batch is
a padded COO block: ``[B, K]`` arrays of table keys, field ids (slots),
values, and a validity mask, plus per-example labels and weights.  Pad
feature entries carry ``mask=0`` and key 0; pad examples (tail of the
last batch of a shard) carry ``weight=0`` so the mean-over-batch
gradient (reference: lr_worker.cc:116-118 divides by row count) uses
the true example count.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def narrow_keys_i32(keys: np.ndarray) -> np.ndarray:
    """THE sanctioned uint64→int32 key narrowing (analysis rule XF011).

    Batch key planes are int32 (XLA gather/scatter indices), but the
    feature key space is uint64 (hashed fids, io/hashing.py) — every
    narrowing is only safe AFTER reduction mod ``table_size``
    (table_size_log2 <= 30, config.py).  Ad-hoc ``.astype(np.int32)``
    casts scattered through the host path would silently WRAP if a
    future table-size bump (or an unreduced 64-bit key) ever reached
    one; this helper is the single audited choke point: already-int32
    input passes through free, anything wider is range-checked before
    the cast (the same reject-never-wrap contract as pack_batch and
    the native parser's -2 return).
    """
    a = np.asarray(keys)
    if a.dtype == np.int32:
        return a
    if a.size and (
        int(a.min()) < np.iinfo(np.int32).min
        or int(a.max()) > np.iinfo(np.int32).max
    ):
        raise ValueError(
            "narrow_keys_i32: key exceeds int32 — reduce full 64-bit "
            "keys mod table_size before narrowing (reject, never wrap)"
        )
    return a.astype(np.int32)


@dataclasses.dataclass
class Batch:
    keys: np.ndarray  # int32 [B, K] — row index into the hashed weight table
    slots: np.ndarray  # int32 [B, K] — field/group id (reference fgid)
    vals: np.ndarray  # float32 [B, K] — feature value (all-1 in hash mode)
    mask: np.ndarray  # float32 [B, K] — 1 for real feature entries
    labels: np.ndarray  # float32 [B] — binary labels
    weights: np.ndarray  # float32 [B] — 1 for real examples, 0 for padding
    # Optional hot section (frequency-head keys < hot_size, served by the
    # MXU path — ops/hot.py): [B, Kh] arrays, Kh = 0 when disabled.  The
    # main arrays above then form the "cold" DMA-path section; a sample's
    # logical feature list is the concatenation of both sections.
    hot_keys: np.ndarray | None = None
    hot_slots: np.ndarray | None = None
    hot_vals: np.ndarray | None = None
    hot_mask: np.ndarray | None = None

    def __post_init__(self) -> None:
        if self.hot_keys is None:
            b = self.keys.shape[0]
            self.hot_keys = np.zeros((b, 0), np.int32)
            self.hot_slots = np.zeros((b, 0), np.int32)
            self.hot_vals = np.zeros((b, 0), np.float32)
            self.hot_mask = np.zeros((b, 0), np.float32)

    @property
    def batch_size(self) -> int:
        return int(self.keys.shape[0])

    @property
    def max_nnz(self) -> int:
        return int(self.keys.shape[1])

    @property
    def hot_nnz(self) -> int:
        return int(self.hot_keys.shape[1])

    def num_real(self) -> int:
        return int(self.weights.sum())


@dataclasses.dataclass
class ParsedBlock:
    """CSR view of one parsed text block (pre-padding)."""

    labels: np.ndarray  # float32 [n]
    row_ptr: np.ndarray  # int64 [n+1]
    keys: np.ndarray  # int64 [nnz] — already reduced mod table_size
    slots: np.ndarray  # int32 [nnz]
    vals: np.ndarray  # float32 [nnz]

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])


def split_hot(
    keys: np.ndarray,
    slots: np.ndarray,
    vals: np.ndarray,
    mask: np.ndarray,
    hot_size: int,
    hot_nnz: int,
) -> dict[str, np.ndarray]:
    """Steer padded [B, Ktot] feature entries into a hot section
    ([B, hot_nnz], keys < hot_size) and a cold section ([B, Ktot -
    hot_nnz], everything else).

    Per row, the first ``hot_nnz`` hot entries (in original order) go to
    the hot section; hot overflow spills into the cold section — which
    is always correct, since the cold DMA path addresses the full table
    including rows [0, hot_size).  Cold entries beyond the cold
    capacity are truncated, the same semantics as the overall max_nnz
    cap.  All O(B*Ktot) vectorized numpy; no per-row loops.
    """
    b, ktot = keys.shape
    kh = hot_nnz
    kc = ktot - kh
    valid = mask > 0
    is_hot = valid & (keys < hot_size)
    hot_rank = np.cumsum(is_hot, axis=1) - 1
    to_hot = is_hot & (hot_rank < kh)
    eff_cold = valid & ~to_hot
    cold_rank = np.cumsum(eff_cold, axis=1) - 1
    to_cold = eff_cold & (cold_rank < kc)

    def compact(arr, sel, rank, width, dtype):
        """Left-compact arr[sel] into [b, width] rows; arr=None writes the
        constant 1.0 (the mask) without materializing a ones array."""
        out = np.zeros((b, width), dtype=dtype)
        r, c = np.nonzero(sel)
        out[r, rank[sel]] = 1.0 if arr is None else arr[r, c]
        return out

    return {
        "hot_keys": compact(keys, to_hot, hot_rank, kh, np.int32),
        "hot_slots": compact(slots, to_hot, hot_rank, kh, np.int32),
        "hot_vals": compact(vals, to_hot, hot_rank, kh, np.float32),
        "hot_mask": compact(None, to_hot, hot_rank, kh, np.float32),
        "keys": compact(keys, to_cold, cold_rank, kc, np.int32),
        "slots": compact(slots, to_cold, cold_rank, kc, np.int32),
        "vals": compact(vals, to_cold, cold_rank, kc, np.float32),
        "mask": compact(None, to_cold, cold_rank, kc, np.float32),
    }


def make_batch(
    keys: np.ndarray,
    slots: np.ndarray,
    vals: np.ndarray,
    mask: np.ndarray,
    labels: np.ndarray,
    weights: np.ndarray,
    hot_size: int = 0,
    hot_nnz: int = 0,
) -> Batch:
    """Build a Batch from padded [B, Ktot] feature arrays, steering
    entries into hot/cold sections when ``hot_size > 0`` (the single
    construction point shared by pack_batch, prepare_batch, and the
    bench/driver synthetic-batch builders)."""
    if not hot_size:
        return Batch(
            keys=keys, slots=slots, vals=vals, mask=mask,
            labels=labels, weights=weights,
        )
    return Batch(
        labels=labels,
        weights=weights,
        **split_hot(keys, slots, vals, mask, hot_size, hot_nnz),
    )


def remap_batch(
    batch: Batch,
    remap: np.ndarray | None,
    hot_size: int,
    hot_nnz: int,
) -> Batch:
    """Bring an externally built Batch (raw hash-space keys) into a
    hot-table model's key space: apply the frequency remap (io/freq.py)
    and re-steer the hot/cold sections.  Loader-produced batches are
    already remapped at parse/pack time; this is for user-supplied
    batches (api.XFlow.predict_batch, serve.PredictEngine).  The ONE
    copy of the remap-and-steer rule, shared by Trainer.prepare_batch
    and the serving engine so the two paths cannot drift.

    No-op when ``remap`` is None (model trained without a hot table).
    """
    if remap is None:
        return batch
    # merge any existing hot section back, remap, then re-steer (a
    # remapped key may cross the hot/cold boundary in either direction);
    # pad by hot_nnz columns so the post-split cold capacity equals the
    # full incoming width — even if every incoming entry lands cold,
    # nothing is truncated on re-steer
    b = batch.batch_size
    pad_i = np.zeros((b, hot_nnz), np.int32)
    pad_f = np.zeros((b, hot_nnz), np.float32)
    keys = np.concatenate([batch.hot_keys, batch.keys, pad_i], axis=1)
    slots = np.concatenate([batch.hot_slots, batch.slots, pad_i], axis=1)
    vals = np.concatenate([batch.hot_vals, batch.vals, pad_f], axis=1)
    mask = np.concatenate([batch.hot_mask, batch.mask, pad_f], axis=1)
    keys = narrow_keys_i32(np.where(mask > 0, remap[keys], 0))
    return make_batch(
        keys, slots, vals, mask, batch.labels, batch.weights,
        hot_size, hot_nnz,
    )


def pad_batch_rows(batch: Batch, to: int) -> Batch:
    """Extend a Batch to ``to`` rows with zero-weight padding examples
    (mask/weights 0 — no-ops through predict and training alike).  Used
    by the serving engine to snap request batches onto its fixed
    compile-shape buckets."""
    extra = to - batch.batch_size
    if extra < 0:
        raise ValueError(
            f"pad_batch_rows: batch has {batch.batch_size} rows, "
            f"cannot shrink to {to}"
        )
    if extra == 0:
        return batch

    def pad(a: np.ndarray) -> np.ndarray:
        return np.concatenate(
            [a, np.zeros((extra,) + a.shape[1:], a.dtype)]
        )

    return Batch(
        keys=pad(batch.keys),
        slots=pad(batch.slots),
        vals=pad(batch.vals),
        mask=pad(batch.mask),
        labels=pad(batch.labels),
        weights=pad(batch.weights),
        hot_keys=pad(batch.hot_keys),
        hot_slots=pad(batch.hot_slots),
        hot_vals=pad(batch.hot_vals),
        hot_mask=pad(batch.hot_mask),
    )


def pack_batch(
    block: ParsedBlock,
    start: int,
    end: int,
    batch_size: int,
    max_nnz: int,
    hot_size: int = 0,
    hot_nnz: int = 0,
) -> Batch:
    """Pack samples [start, end) of a CSR block into one padded Batch.

    Rows with more than ``max_nnz`` features are truncated (the reference
    has no per-sample feature cap; SURVEY §7 hard part (b)).  With
    ``hot_size > 0``, each row gets ``hot_nnz`` extra slots of hot-key
    capacity and its entries are steered by ``split_hot``.
    """
    n = end - start
    assert 0 < n <= batch_size
    # Keys narrow to int32 batch arrays; reject, never wrap — the same
    # guard the native pack enforces (parser.cc returns -2).  Scoped to
    # the packed slice so the check is O(slice nnz).
    lo, hi = int(block.row_ptr[start]), int(block.row_ptr[end])
    if hi > lo:
        kslice = block.keys[lo:hi]
        if kslice.min() < 0 or kslice.max() > np.iinfo(np.int32).max:
            raise ValueError(
                "pack_batch: a key exceeds int32 — table_size too large "
                "for the int32 batch arrays (full 64-bit keys must be "
                "reduced before packing)"
            )
    ktot = max_nnz + (hot_nnz if hot_size else 0)
    labels = np.zeros(batch_size, dtype=np.float32)
    weights = np.zeros(batch_size, dtype=np.float32)
    labels[:n] = block.labels[start:end]
    weights[:n] = 1.0

    starts = block.row_ptr[start:end]
    ends = block.row_ptr[start + 1 : end + 1]
    counts = np.minimum(ends - starts, ktot)
    # vectorized ragged→padded gather: position j of row i reads CSR slot
    # starts[i]+j while j < counts[i]
    j = np.arange(ktot, dtype=np.int64)[None, :]
    valid = j < counts[:, None]  # [n, K]
    src = np.where(valid, starts[:, None] + j, 0)

    def pad_gather(flat: np.ndarray, dtype) -> np.ndarray:
        out = np.zeros((batch_size, ktot), dtype=dtype)
        if len(flat):
            out[:n] = np.where(valid, flat[src], 0)
        return out

    keys = pad_gather(block.keys, np.int32)
    slots = pad_gather(block.slots, np.int32)
    vals = pad_gather(block.vals, np.float32)
    mask = np.concatenate(
        [
            valid.astype(np.float32),
            np.zeros((batch_size - n, ktot), np.float32),
        ]
    )
    return make_batch(
        keys, slots, vals, mask, labels, weights, hot_size, hot_nnz
    )
