"""Padded static-shape minibatch representation.

The reference's minibatch is ``Data{fea_matrix: vector<vector<kv>>,
label: vector<int>}`` with ``kv = {fgid, fid, val}`` (io.h:18-22,61-65) —
ragged rows of sparse features.  XLA wants static shapes, so a batch is
a padded COO block: ``[B, K]`` arrays of table keys, field ids (slots),
values, and a validity mask, plus per-example labels and weights.  Pad
feature entries carry ``mask=0`` and key 0; pad examples (tail of the
last batch of a shard) carry ``weight=0`` so the mean-over-batch
gradient (reference: lr_worker.cc:116-118 divides by row count) uses
the true example count.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class Batch:
    keys: np.ndarray  # int32 [B, K] — row index into the hashed weight table
    slots: np.ndarray  # int32 [B, K] — field/group id (reference fgid)
    vals: np.ndarray  # float32 [B, K] — feature value (all-1 in hash mode)
    mask: np.ndarray  # float32 [B, K] — 1 for real feature entries
    labels: np.ndarray  # float32 [B] — binary labels
    weights: np.ndarray  # float32 [B] — 1 for real examples, 0 for padding

    @property
    def batch_size(self) -> int:
        return int(self.keys.shape[0])

    @property
    def max_nnz(self) -> int:
        return int(self.keys.shape[1])

    def num_real(self) -> int:
        return int(self.weights.sum())


@dataclasses.dataclass
class ParsedBlock:
    """CSR view of one parsed text block (pre-padding)."""

    labels: np.ndarray  # float32 [n]
    row_ptr: np.ndarray  # int64 [n+1]
    keys: np.ndarray  # int64 [nnz] — already reduced mod table_size
    slots: np.ndarray  # int32 [nnz]
    vals: np.ndarray  # float32 [nnz]

    @property
    def num_samples(self) -> int:
        return int(self.labels.shape[0])


def pack_batch(
    block: ParsedBlock,
    start: int,
    end: int,
    batch_size: int,
    max_nnz: int,
) -> Batch:
    """Pack samples [start, end) of a CSR block into one padded Batch.

    Rows with more than ``max_nnz`` features are truncated (the reference
    has no per-sample feature cap; SURVEY §7 hard part (b)).
    """
    n = end - start
    assert 0 < n <= batch_size
    labels = np.zeros(batch_size, dtype=np.float32)
    weights = np.zeros(batch_size, dtype=np.float32)
    labels[:n] = block.labels[start:end]
    weights[:n] = 1.0

    starts = block.row_ptr[start:end]
    ends = block.row_ptr[start + 1 : end + 1]
    counts = np.minimum(ends - starts, max_nnz)
    # vectorized ragged→padded gather: position j of row i reads CSR slot
    # starts[i]+j while j < counts[i]
    j = np.arange(max_nnz, dtype=np.int64)[None, :]
    valid = j < counts[:, None]  # [n, K]
    src = np.where(valid, starts[:, None] + j, 0)

    def pad_gather(flat: np.ndarray, dtype) -> np.ndarray:
        out = np.zeros((batch_size, max_nnz), dtype=dtype)
        if len(flat):
            out[:n] = np.where(valid, flat[src], 0)
        return out

    return Batch(
        keys=pad_gather(block.keys, np.int32),
        slots=pad_gather(block.slots, np.int32),
        vals=pad_gather(block.vals, np.float32),
        mask=np.concatenate(
            [
                valid.astype(np.float32),
                np.zeros((batch_size - n, max_nnz), np.float32),
            ]
        ),
        labels=labels,
        weights=weights,
    )
