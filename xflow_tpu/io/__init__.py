from xflow_tpu.io.hashing import murmur64, murmur64_batch
from xflow_tpu.io.libffm import parse_block, BlockReader
from xflow_tpu.io.loader import ShardLoader, shard_path
from xflow_tpu.io.batch import Batch
from xflow_tpu.io.compact import CompactBatch, compact_batch

__all__ = [
    "murmur64",
    "murmur64_batch",
    "parse_block",
    "BlockReader",
    "ShardLoader",
    "shard_path",
    "Batch",
    "CompactBatch",
    "compact_batch",
]
