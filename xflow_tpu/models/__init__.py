from xflow_tpu.models.base import Model, TableSpec
from xflow_tpu.models.lr import LRModel
from xflow_tpu.models.fm import FMModel
from xflow_tpu.models.mvm import MVMModel


def make_model(cfg) -> Model:
    # Reference model dispatch: main.cc:27-45, argv[3] '0'→LR '1'→FM '2'→MVM.
    if cfg.model == "lr":
        return LRModel()
    if cfg.model == "fm":
        return FMModel(v_dim=cfg.v_dim, v_init_scale=cfg.v_init_scale)
    if cfg.model == "mvm":
        return MVMModel(
            v_dim=cfg.v_dim,
            v_init_scale=cfg.v_init_scale,
            max_fields=cfg.max_fields,
        )
    raise ValueError(f"unknown model {cfg.model!r}")


__all__ = ["Model", "TableSpec", "LRModel", "FMModel", "MVMModel", "make_model"]
