"""Model registry — the ONE place a family plugs into the system.

The reference dispatches models by positional index (main.cc:27-45,
argv[3] '0'→LR '1'→FM '2'→MVM); this repo's five-then-seven families
used to be re-enumerated as string literals in config validation, the
CLI choices, the C-ABI docs, and the bench scripts — adding a family
meant a scavenger hunt.  Now a family registers HERE once:

* ``build`` — Config -> Model instance (the only constructor callers
  use; serve/engine.py, trainer.py, the C ABI all route through
  ``make_model``);
* ``retrieval`` — the family factors into user/item towers
  (``user_embed``/``item_embed``) whose item side exports a serve-time
  top-k index (serve/artifact.py::export_item_index,
  PredictEngine.topk).  Non-retrieval families refuse the index/top-k
  surface with an actionable error instead of scoring garbage.

``Config.__post_init__`` validates ``cfg.model`` against
``model_names()``, ``xflow_tpu.train`` builds its ``--model`` choices
from it, and ``scripts/bench_models.py`` enumerates it (a registered
family without a bench geometry fails that script loudly) — so a new
family is config-valid, CLI-reachable, C-ABI-servable, and
bench-tracked by virtue of this one entry.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

from xflow_tpu.models.base import AutodiffModel, Model, TableSpec
from xflow_tpu.models.dcn import DCNModel
from xflow_tpu.models.ffm import FFMModel
from xflow_tpu.models.fm import FMModel
from xflow_tpu.models.lr import LRModel
from xflow_tpu.models.mvm import MVMModel
from xflow_tpu.models.two_tower import TwoTowerModel
from xflow_tpu.models.wide_deep import WideDeepModel


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    name: str
    build: Callable[..., Model]  # (cfg: Config) -> Model
    description: str
    #: user/item-tower factorization: item tower exports a serve-time
    #: top-k index (serve/artifact.py, PredictEngine.topk)
    retrieval: bool = False


REGISTRY: dict[str, ModelFamily] = {}


def register_model(family: ModelFamily) -> ModelFamily:
    """Add a family (refuses duplicate names — two registrations for
    one name is always a bug, not an override)."""
    if family.name in REGISTRY:
        raise ValueError(f"model family {family.name!r} already registered")
    REGISTRY[family.name] = family
    return family


def model_names() -> tuple[str, ...]:
    return tuple(REGISTRY)


def model_family(name: str) -> ModelFamily:
    fam = REGISTRY.get(name)
    if fam is None:
        raise ValueError(
            f"unknown model {name!r} (registered families: "
            f"{', '.join(REGISTRY)})"
        )
    return fam


def make_model(cfg) -> Model:
    # Reference model dispatch: main.cc:27-45, argv[3] '0'→LR '1'→FM
    # '2'→MVM; everything else is a capability extension registered
    # above the reference's zoo.
    return model_family(cfg.model).build(cfg)


register_model(ModelFamily(
    "lr", lambda cfg: LRModel(),
    "sparse logistic regression (reference model 0)",
))
register_model(ModelFamily(
    "fm",
    lambda cfg: FMModel(v_dim=cfg.v_dim, v_init_scale=cfg.v_init_scale),
    "2-way factorization machine (reference model 1)",
))
register_model(ModelFamily(
    "mvm",
    lambda cfg: MVMModel(
        v_dim=cfg.v_dim,
        v_init_scale=cfg.v_init_scale,
        max_fields=cfg.max_fields,
    ),
    "multi-view machine (reference model 2)",
))
register_model(ModelFamily(
    "ffm",
    lambda cfg: FFMModel(
        v_dim=cfg.ffm_v_dim,
        max_fields=cfg.max_fields,
        v_init_scale=cfg.v_init_scale,
    ),
    "field-aware FM (extension; BASELINE.json target)",
))
register_model(ModelFamily(
    "wide_deep",
    lambda cfg: WideDeepModel(
        emb_dim=cfg.emb_dim,
        hidden=cfg.hidden_dim,
        max_fields=cfg.max_fields,
        v_init_scale=cfg.v_init_scale,
    ),
    "wide & deep: sparse linear + embedding MLP (extension)",
))
register_model(ModelFamily(
    "two_tower",
    lambda cfg: TwoTowerModel(
        emb_dim=cfg.emb_dim,
        tower_dim=cfg.tower_dim,
        hidden=cfg.hidden_dim,
        max_fields=cfg.max_fields,
        split_field=cfg.tower_split_field,
        v_init_scale=cfg.v_init_scale,
    ),
    "two-tower retrieval: dot-product user/item towers over disjoint "
    "field groups; item tower exports the serve-time top-k index",
    retrieval=True,
))
register_model(ModelFamily(
    "dcn",
    lambda cfg: DCNModel(
        emb_dim=cfg.emb_dim,
        hidden=cfg.hidden_dim,
        cross_layers=cfg.cross_layers,
        max_fields=cfg.max_fields,
        v_init_scale=cfg.v_init_scale,
    ),
    "deep & cross ranker: explicit bounded-degree feature crosses + "
    "MLP over the embedding tower (the cascade's ranking stage)",
))


__all__ = [
    "AutodiffModel",
    "Model",
    "ModelFamily",
    "REGISTRY",
    "TableSpec",
    "LRModel",
    "FMModel",
    "MVMModel",
    "FFMModel",
    "WideDeepModel",
    "TwoTowerModel",
    "DCNModel",
    "make_model",
    "model_family",
    "model_names",
    "register_model",
]
