from xflow_tpu.models.base import AutodiffModel, Model, TableSpec
from xflow_tpu.models.lr import LRModel
from xflow_tpu.models.fm import FMModel
from xflow_tpu.models.mvm import MVMModel
from xflow_tpu.models.ffm import FFMModel
from xflow_tpu.models.wide_deep import WideDeepModel


def make_model(cfg) -> Model:
    # Reference model dispatch: main.cc:27-45, argv[3] '0'→LR '1'→FM '2'→MVM;
    # ffm/wide_deep are extensions (BASELINE.json target configs).
    if cfg.model == "lr":
        return LRModel()
    if cfg.model == "fm":
        return FMModel(v_dim=cfg.v_dim, v_init_scale=cfg.v_init_scale)
    if cfg.model == "mvm":
        return MVMModel(
            v_dim=cfg.v_dim,
            v_init_scale=cfg.v_init_scale,
            max_fields=cfg.max_fields,
        )
    if cfg.model == "ffm":
        return FFMModel(
            v_dim=cfg.ffm_v_dim,
            max_fields=cfg.max_fields,
            v_init_scale=cfg.v_init_scale,
        )
    if cfg.model == "wide_deep":
        return WideDeepModel(
            emb_dim=cfg.emb_dim,
            hidden=cfg.hidden_dim,
            max_fields=cfg.max_fields,
            v_init_scale=cfg.v_init_scale,
        )
    raise ValueError(f"unknown model {cfg.model!r}")


__all__ = [
    "AutodiffModel",
    "Model",
    "TableSpec",
    "LRModel",
    "FMModel",
    "MVMModel",
    "FFMModel",
    "WideDeepModel",
    "make_model",
]
