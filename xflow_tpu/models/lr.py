"""Sparse logistic regression (reference: src/model/lr/lr_worker.{h,cc}).

Forward: wx[b] = sum of the gathered w entries for the sample's features
(lr_worker.cc:121-143 — the reference's two-pointer join of sorted
sample keys against the pulled unique-key slice; here a masked gather
reduction).  The reference's hash-mode features are binary so it sums
bare w; we multiply by the feature value, which is 1.0 in hash mode
(parity) and carries real values in numeric mode (superset).

Gradient: d wx / d w_i = x_i (= 1 for binary); the train step scales by
(sigma(wx) - y) / batch_n, matching calculate_gradient's mean-over-batch
(lr_worker.cc:100-119).

Expressed through models/blocks.py (masked_x + linear_term) — the
blocks ARE the pre-refactor expressions, bitwise
(tests/test_models.py no-regression pins).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import BatchArrays, TableSpec
from xflow_tpu.models.blocks import linear_term, masked_x


class LRModel:
    name = "lr"
    # never reads batch["slots"] — eligible for the compact wire format
    # (parallel/step.py put_batch: keys+labels only over the host link)
    uses_slots = False

    def tables(self) -> list[TableSpec]:
        # w entries are zero-initialized server-side in the reference
        # (ftrl.h:50-53 default-constructed map entries).
        return [TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32))]

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        return linear_term(rows["w"], masked_x(batch))

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = masked_x(batch)
        return {"w": x[..., None]}
