"""Field-aware Factorization Machine.

Capability extension beyond the reference's model zoo (BASELINE.json
configs list "Field-aware FM (FFM) on Avazu CTR" as a target workload;
the reference itself ships only LR/FM/MVM).  Standard FFM:

    logit = sum_i w_i x_i
          + sum_{i<j} < v[k_i, f_j, :], v[k_j, f_i, :] > x_i x_j

Each feature key holds one latent vector PER FIELD: the v table is
[T, max_fields * v_dim], viewed as [T, F, D].  Fields beyond
max_fields contribute nothing (their one-hot row is zero), matching
MVM's field handling.

Pure autodiff model — no reference forward/backward quirks to
reproduce.

The pair interaction uses the field-aggregated identity (round-2
restructure; the naive form materializes [B, K, K, D] pair tensors —
tens of GB at bench shapes):

    S[b, f1, f2, :] = sum_{i: field(i)=f1} x_i * v[k_i, f2, :]
    sum_{i<j} <v[k_i,f_j], v[k_j,f_i]> x_i x_j
        = 1/2 ( sum_{f1,f2} <S[f1,f2], S[f2,f1]>
                - sum_i x_i^2 ||v[k_i, f_i]||^2 )

which is O(B*K*F^2*D) compute via one MXU batch-matmul over K and
O(B*F^2*D) memory — same-field pairs included, diagonal (i=i)
subtracted, both orderings halved, exactly the i<j sum.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec
from xflow_tpu.models.blocks import (
    ffm_field_interaction,
    linear_term,
    masked_x,
    valid_fields,
)


@dataclasses.dataclass(frozen=True)
class FFMModel(AutodiffModel):
    v_dim: int = 4
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "ffm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.max_fields * self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                # v rows are max_fields*v_dim ≈ 156 lanes wide: the
                # one-hot h2*dim traffic exceeds the DMA cost it
                # replaces, so only w rides the MXU hot path
                # (TableSpec.hot rationale)
                hot=False,
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        f = self.max_fields
        x = masked_x(batch)  # [B, K]
        linear = linear_term(rows["w"], x)

        # negative field ids dropped, matching MVM/Wide&Deep
        valid = valid_fields(batch["slots"], batch["mask"], f)
        x_eff = jnp.where(valid, x, 0.0)
        slot = jnp.clip(batch["slots"], 0, f - 1)  # [B, K]
        # the field-aggregated pairwise identity + its TPU layout
        # discipline live in blocks.ffm_field_interaction (E = F*D
        # stays the minor dim; no [B, K, K, *] pair tensors)
        return linear + ffm_field_interaction(
            rows["v"], x_eff, slot, valid, f, self.v_dim
        )

    def logit_pairwise(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        """Naive O(B*K^2*D) pairwise form — the definition the aggregated
        ``logit`` must match (kept as the equivalence oracle for
        tests/test_extended_models.py; do not use at scale)."""
        b, k = batch["keys"].shape
        f, d = self.max_fields, self.v_dim
        x = batch["vals"] * batch["mask"]  # [B, K]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        v = rows["v"].reshape(b, k, f, d)
        slot = jnp.clip(batch["slots"], 0, f - 1)
        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < f) & (batch["mask"] > 0)
        )
        # v_for[b, i, j, :] = v[key_i, field_of_j, :]
        v_for = v[
            jnp.arange(b)[:, None, None],
            jnp.arange(k)[None, :, None],
            slot[:, None, :],
            :,
        ]  # [B, K(i), K(j), D]
        inter = jnp.einsum("bijd,bjid->bij", v_for, v_for)
        xx = x[:, :, None] * x[:, None, :]
        pair_valid = (
            valid[:, :, None]
            & valid[:, None, :]
            & (jnp.arange(k)[:, None] < jnp.arange(k)[None, :])
        )
        return linear + jnp.sum(
            jnp.where(pair_valid, inter * xx, 0.0), axis=(1, 2)
        )
