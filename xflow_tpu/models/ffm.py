"""Field-aware Factorization Machine.

Capability extension beyond the reference's model zoo (BASELINE.json
configs list "Field-aware FM (FFM) on Avazu CTR" as a target workload;
the reference itself ships only LR/FM/MVM).  Standard FFM:

    logit = sum_i w_i x_i
          + sum_{i<j} < v[k_i, f_j, :], v[k_j, f_i, :] > x_i x_j

Each feature key holds one latent vector PER FIELD: the v table is
[T, max_fields * v_dim], viewed as [T, F, D].  Fields beyond
max_fields contribute nothing (their one-hot row is zero), matching
MVM's field handling.

Pure autodiff model — no reference forward/backward quirks to
reproduce.

The pair interaction uses the field-aggregated identity (round-2
restructure; the naive form materializes [B, K, K, D] pair tensors —
tens of GB at bench shapes):

    S[b, f1, f2, :] = sum_{i: field(i)=f1} x_i * v[k_i, f2, :]
    sum_{i<j} <v[k_i,f_j], v[k_j,f_i]> x_i x_j
        = 1/2 ( sum_{f1,f2} <S[f1,f2], S[f2,f1]>
                - sum_i x_i^2 ||v[k_i, f_i]||^2 )

which is O(B*K*F^2*D) compute via one MXU batch-matmul over K and
O(B*F^2*D) memory — same-field pairs included, diagonal (i=i)
subtracted, both orderings halved, exactly the i<j sum.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec


@dataclasses.dataclass(frozen=True)
class FFMModel(AutodiffModel):
    v_dim: int = 4
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "ffm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.max_fields * self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                # v rows are max_fields*v_dim ≈ 156 lanes wide: the
                # one-hot h2*dim traffic exceeds the DMA cost it
                # replaces, so only w rides the MXU hot path
                # (TableSpec.hot rationale)
                hot=False,
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        b, k = batch["keys"].shape
        f, d = self.max_fields, self.v_dim
        x = batch["vals"] * batch["mask"]  # [B, K]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < f) & (batch["mask"] > 0)
        )  # [B, K] — negative field ids dropped, matching MVM/Wide&Deep
        x_eff = jnp.where(valid, x, 0.0)
        slot = jnp.clip(batch["slots"], 0, f - 1)  # [B, K]
        # one-hot of each feature's own field; zero row for invalid
        onehot = (
            (slot[:, :, None] == jnp.arange(f)[None, None, :])
            & valid[:, :, None]
        ).astype(rows["v"].dtype)  # [B, K, F]

        # TPU layout constraint: every materialized tensor keeps the
        # flattened E = F*D as its minor dimension.  A [.., D=4]-minor
        # operand gets T(8,128) lane padding — 32x physical memory; the
        # first shape of this model OOM'd a 16 GB chip at B=32768 with
        # a 26 GB copy of the [B,K,F,D] pair operand (round-4 log).
        vx = rows["v"] * x_eff[:, :, None]  # [B, K, E]
        # field-aggregated sums: one batch matmul contracting K (MXU);
        # operand minor dims are F (padded 39->128 one-hot) and E=156
        # (->256) — no 32x blowup, no [B, K, K, *] pair tensors
        s = jnp.einsum("bkf,bke->bfe", onehot, vx)  # [B, F, E]

        # cross term sum_{f1,f2,d} S[b,f1,f2,d] * S[b,f2,f1,d]: the
        # (f1<->f2, d fixed) transpose + multiply + reduce stays an
        # elementwise fusion over s read twice — never a dot_general,
        # whose operand copies would resurrect the D-minor layout
        s4 = s.reshape(b, f, f, d)
        cross = jnp.sum(
            s4 * jnp.transpose(s4, (0, 2, 1, 3)), axis=(1, 2, 3)
        )
        # subtract the i == i diagonal: x_i^2 * ||v[k_i, f_i, :]||^2.
        # Select each key's own-field block of E elementwise (e//D ==
        # slot) instead of take_along_axis — same fusion argument.
        eslot = (jnp.arange(f * d) // d).astype(slot.dtype)  # [E]
        emask = eslot[None, None, :] == slot[:, :, None]  # [B, K, E]
        diag = jnp.sum(jnp.where(emask, vx * vx, 0.0), axis=(1, 2))
        return linear + 0.5 * (cross - diag)

    def logit_pairwise(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        """Naive O(B*K^2*D) pairwise form — the definition the aggregated
        ``logit`` must match (kept as the equivalence oracle for
        tests/test_extended_models.py; do not use at scale)."""
        b, k = batch["keys"].shape
        f, d = self.max_fields, self.v_dim
        x = batch["vals"] * batch["mask"]  # [B, K]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        v = rows["v"].reshape(b, k, f, d)
        slot = jnp.clip(batch["slots"], 0, f - 1)
        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < f) & (batch["mask"] > 0)
        )
        # v_for[b, i, j, :] = v[key_i, field_of_j, :]
        v_for = v[
            jnp.arange(b)[:, None, None],
            jnp.arange(k)[None, :, None],
            slot[:, None, :],
            :,
        ]  # [B, K(i), K(j), D]
        inter = jnp.einsum("bijd,bjid->bij", v_for, v_for)
        xx = x[:, :, None] * x[:, None, :]
        pair_valid = (
            valid[:, :, None]
            & valid[:, None, :]
            & (jnp.arange(k)[:, None] < jnp.arange(k)[None, :])
        )
        return linear + jnp.sum(
            jnp.where(pair_valid, inter * xx, 0.0), axis=(1, 2)
        )
