"""Field-aware Factorization Machine.

Capability extension beyond the reference's model zoo (BASELINE.json
configs list "Field-aware FM (FFM) on Avazu CTR" as a target workload;
the reference itself ships only LR/FM/MVM).  Standard FFM:

    logit = sum_i w_i x_i
          + sum_{i<j} < v[k_i, f_j, :], v[k_j, f_i, :] > x_i x_j

Each feature key holds one latent vector PER FIELD: the v table is
[T, max_fields * v_dim], viewed as [T, F, D].  Fields beyond
max_fields contribute nothing (their one-hot row is zero), matching
MVM's field handling.

Pure autodiff model — no reference forward/backward quirks to
reproduce.  The O(K^2) pair interaction is computed as a dense
[B, K, K] einsum (MXU-friendly) with the diagonal and invalid pairs
masked.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec


@dataclasses.dataclass(frozen=True)
class FFMModel(AutodiffModel):
    v_dim: int = 4
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "ffm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.max_fields * self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
            ),
        ]

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        b, k = batch["keys"].shape
        f, d = self.max_fields, self.v_dim
        x = batch["vals"] * batch["mask"]  # [B, K]
        linear = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        v = rows["v"].reshape(b, k, f, d)  # per-key field-specific vectors
        slot = jnp.clip(batch["slots"], 0, f - 1)  # [B, K]
        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < f) & (batch["mask"] > 0)
        )  # [B, K] — negative field ids dropped, matching MVM/Wide&Deep

        # v_for[b, i, j, :] = v[key_i, field_of_j, :] — gather i's latent
        # vector specific to j's field, for every ordered pair (i, j).
        v_for = v[
            jnp.arange(b)[:, None, None],
            jnp.arange(k)[None, :, None],
            slot[:, None, :],
            :,
        ]  # [B, K(i), K(j), D]

        inter = jnp.einsum("bijd,bjid->bij", v_for, v_for)  # <v_i,fj , v_j,fi>
        xx = x[:, :, None] * x[:, None, :]  # [B, K, K]
        pair_valid = (
            valid[:, :, None]
            & valid[:, None, :]
            & (jnp.arange(k)[:, None] < jnp.arange(k)[None, :])
        )
        return linear + jnp.sum(
            jnp.where(pair_valid, inter * xx, 0.0), axis=(1, 2)
        )
