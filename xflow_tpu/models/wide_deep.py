"""Wide & Deep: sparse linear ("wide") + embedding MLP ("deep").

Capability extension beyond the reference's model zoo (BASELINE.json
configs list "Wide-and-deep (LR + 2-layer MLP) on Criteo-Kaggle").

* Wide: the LR weight table, FTRL-updated like every table.
* Deep: an embedding table [T, emb_dim]; each sample's embeddings are
  field-summed into max_fields buckets (same one-hot trick as MVM, so
  variable features-per-field work under static shapes), concatenated
  to [max_fields * emb_dim], and fed through a 2-layer ReLU MLP whose
  weights are replicated dense parameters.

Autodiff model: table gradients and MLP gradients both come from
jax.grad of the batch loss.  The dense MLP parameters are updated with
plain SGD (config.sgd_lr) regardless of the table optimizer — FTRL's
per-coordinate L1 shrinkage is for sparse one-hot features, not dense
hidden layers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec
from xflow_tpu.models.blocks import (
    field_sum_tower,
    flatten_tower,
    linear_term,
    masked_x,
    mlp_head,
    mlp_head_init,
)


@dataclasses.dataclass(frozen=True)
class WideDeepModel(AutodiffModel):
    emb_dim: int = 8
    hidden: int = 64
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "wide_deep"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "emb",
                self.emb_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def dense_init(self, rng: jax.Array) -> dict:
        # He init for the ReLU layer, small linear head
        # (blocks.mlp_head_init — the lifted pre-refactor geometry).
        return mlp_head_init(
            rng, self.max_fields * self.emb_dim, self.hidden
        )

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        assert dense is not None, "wide_deep requires dense MLP params"
        x = masked_x(batch)  # [B, K]
        wide = linear_term(rows["w"], x)
        # embedding tower + scalar MLP head, both straight off the
        # blocks shelf (field_sum_tower IS the lifted deep half)
        field_emb = field_sum_tower(
            rows["emb"], x, batch["slots"], self.max_fields
        )  # [B, F, E]
        deep = mlp_head(dense, flatten_tower(field_emb))
        return wide + deep
