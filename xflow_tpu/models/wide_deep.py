"""Wide & Deep: sparse linear ("wide") + embedding MLP ("deep").

Capability extension beyond the reference's model zoo (BASELINE.json
configs list "Wide-and-deep (LR + 2-layer MLP) on Criteo-Kaggle").

* Wide: the LR weight table, FTRL-updated like every table.
* Deep: an embedding table [T, emb_dim]; each sample's embeddings are
  field-summed into max_fields buckets (same one-hot trick as MVM, so
  variable features-per-field work under static shapes), concatenated
  to [max_fields * emb_dim], and fed through a 2-layer ReLU MLP whose
  weights are replicated dense parameters.

Autodiff model: table gradients and MLP gradients both come from
jax.grad of the batch loss.  The dense MLP parameters are updated with
plain SGD (config.sgd_lr) regardless of the table optimizer — FTRL's
per-coordinate L1 shrinkage is for sparse one-hot features, not dense
hidden layers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec


@dataclasses.dataclass(frozen=True)
class WideDeepModel(AutodiffModel):
    emb_dim: int = 8
    hidden: int = 64
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "wide_deep"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "emb",
                self.emb_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def dense_init(self, rng: jax.Array) -> dict:
        k1, k2 = jax.random.split(rng)
        in_dim = self.max_fields * self.emb_dim
        # He init for the ReLU layer, small linear head.
        return {
            "w1": jax.random.normal(k1, (in_dim, self.hidden), jnp.float32)
            * jnp.sqrt(2.0 / in_dim),
            "b1": jnp.zeros((self.hidden,), jnp.float32),
            "w2": jax.random.normal(k2, (self.hidden, 1), jnp.float32)
            * jnp.sqrt(1.0 / self.hidden),
            "b2": jnp.zeros((1,), jnp.float32),
        }

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        assert dense is not None, "wide_deep requires dense MLP params"
        x = batch["vals"] * batch["mask"]  # [B, K]
        wide = jnp.sum(rows["w"][..., 0] * x, axis=-1)

        onehot = jax.nn.one_hot(
            batch["slots"], self.max_fields, dtype=x.dtype
        )  # [B, K, F]; out-of-range fields drop out
        embx = rows["emb"] * x[..., None]  # [B, K, E]
        field_emb = jnp.einsum("bkf,bke->bfe", onehot, embx)  # [B, F, E]
        h = field_emb.reshape(field_emb.shape[0], -1)  # [B, F*E]
        h = jax.nn.relu(h @ dense["w1"] + dense["b1"])
        deep = (h @ dense["w2"] + dense["b2"])[:, 0]
        return wide + deep
