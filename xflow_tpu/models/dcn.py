"""Deep & Cross ranker: explicit bounded-degree feature crosses + MLP.

The cascade's RANKING stage (docs/SERVING.md): where the two-tower
retriever is architecturally forbidden from crossing user and item
features (the dot factorization is what makes the index precomputable),
the ranker exists to model exactly those crosses over the few hundred
retrieved candidates.  DCN (Deep & Cross Network) makes the crossing
explicit and cheap:

    x_0     = flattened field-pooled embedding tower  [B, P]
    x_{l+1} = x_0 * (x_l . w_l) + b_l + x_l           (cross stack)
    h       = ReLU(x_0 W1 + b1)                       (deep half)
    logit   = wide + [x_L ; h] W_out + b_out

Each cross layer adds one learned degree of polynomial interaction at
O(P) parameters — the standard alternative to FM/FFM's fixed
second-order forms when the interactions worth modeling are sparse
and data-determined.

Composed from models/blocks.py (field_sum_tower / cross_network /
linear_term); the wide half and the dense-parameter path (replicated
pytree, plain-SGD via parallel/step.py::apply_dense_sgd) are exactly
wide&deep's — no new train-step machinery.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec
from xflow_tpu.models.blocks import (
    cross_network,
    field_sum_tower,
    flatten_tower,
    linear_term,
    masked_x,
)


@dataclasses.dataclass(frozen=True)
class DCNModel(AutodiffModel):
    emb_dim: int = 8
    hidden: int = 64
    cross_layers: int = 2
    max_fields: int = 32
    v_init_scale: float = 1e-2
    name: str = "dcn"

    def __post_init__(self) -> None:
        if self.cross_layers < 1:
            raise ValueError(
                f"dcn cross_layers {self.cross_layers} must be >= 1 "
                "(0 layers is wide&deep — use that family)"
            )

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "emb",
                self.emb_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32)
                    * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def dense_init(self, rng: jax.Array) -> dict:
        kc, k1, ko = jax.random.split(rng, 3)
        p = self.max_fields * self.emb_dim
        # cross weights start small (each layer perturbs the identity
        # path x_l + ...); biases zero; He for the ReLU deep half.
        return {
            "cross_w": jax.random.normal(
                kc, (self.cross_layers, p), jnp.float32
            ) * jnp.sqrt(1.0 / p),
            "cross_b": jnp.zeros((self.cross_layers, p), jnp.float32),
            "w1": jax.random.normal(k1, (p, self.hidden), jnp.float32)
            * jnp.sqrt(2.0 / p),
            "b1": jnp.zeros((self.hidden,), jnp.float32),
            "w_out": jax.random.normal(
                ko, (p + self.hidden, 1), jnp.float32
            ) * jnp.sqrt(1.0 / (p + self.hidden)),
            "b_out": jnp.zeros((1,), jnp.float32),
        }

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        assert dense is not None, "dcn requires dense cross/MLP params"
        x = masked_x(batch)  # [B, K]
        wide = linear_term(rows["w"], x)
        x0 = flatten_tower(field_sum_tower(
            rows["emb"], x, batch["slots"], self.max_fields
        ))  # [B, P]
        xc = cross_network(x0, dense["cross_w"], dense["cross_b"])
        h = jax.nn.relu(x0 @ dense["w1"] + dense["b1"])
        out = (
            jnp.concatenate([xc, h], axis=-1) @ dense["w_out"]
            + dense["b_out"]
        )[:, 0]
        return wide + out
