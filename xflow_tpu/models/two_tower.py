"""Two-tower retrieval: user/item towers over disjoint field groups.

The other half of a real recommender stack (arXiv:2501.10546): before
a ranker can point-score candidates, something has to GENERATE them
from a catalog of millions.  The two-tower factorization makes that
tractable: the logit is a dot product

    logit = < u(user features), i(item features) >

where each tower only reads its own field group — fields
``[0, split_field)`` are user-side, ``[split_field, max_fields)`` are
item-side.  Because the item tower is independent of the user, every
item's embedding can be computed ONCE offline and frozen into a
serve-time index (serve/artifact.py::export_item_index); retrieval is
then one [B, Dt] user-tower pass plus a dot-product scan + top-k over
the index (PredictEngine.topk) — no per-candidate model evaluation.

Training is standard BCE over the dot product on (user, item, click)
rows — an AutodiffModel riding the existing gather→tower→reduce step:
one shared ``emb`` table (both towers draw from the same hashed key
space; the field split keeps their rows disjoint in practice), a
2-layer MLP tower per side (replicated dense params, plain-SGD updated
like wide&deep's head).  Built entirely from models/blocks.py:
field_sum_tower → slice the field range → mlp_tower → dot_interaction.

**Bias lanes.**  Each tower's MLP emits ``tower_dim + 1`` lanes; the
last is a per-side BIAS folded into the dot by augmentation —
``u' = [u, b_u, 1]``, ``i' = [i, 1, b_i]`` so ``<u', i'> = <u, i> +
b_u + b_i``.  A bare dot cannot represent ADDITIVE structure (a
user-only propensity plus an item-only popularity — the dominant
terms of real CTR and exactly the planted signal of the convergence
proxy: measured AUC 0.510 after 2 epochs without the lanes vs 0.640
with, docs/CONVERGENCE.md); the bias lanes add it while keeping the
score a PURE dot product, so the serve-time index scan
(PredictEngine.topk over [N, tower_dim + 2] rows) is unchanged —
item popularity simply lives inside each index row.

Out-of-range fields drop out of the one-hot like every other family;
features on the WRONG side of the split simply pool into that side's
tower (the slot says which tower owns the feature).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import AutodiffModel, BatchArrays, TableSpec
from xflow_tpu.models.blocks import (
    dot_interaction,
    field_sum_tower,
    masked_x,
    mlp_tower,
    mlp_tower_init,
)


@dataclasses.dataclass(frozen=True)
class TwoTowerModel(AutodiffModel):
    emb_dim: int = 8
    tower_dim: int = 16
    hidden: int = 64
    max_fields: int = 32
    split_field: int = 16  # fields < split are user-side, >= are item-side
    v_init_scale: float = 1e-2
    name: str = "two_tower"

    def __post_init__(self) -> None:
        if not 0 < self.split_field < self.max_fields:
            raise ValueError(
                f"two_tower split_field {self.split_field} must be in "
                f"(0, max_fields={self.max_fields}): both towers need "
                "at least one field"
            )

    @property
    def index_dim(self) -> int:
        """Serve-time index row width: tower_dim core lanes + the two
        bias-augmentation lanes (module docstring).  PredictEngine.
        attach_item_index validates index shapes against this."""
        return self.tower_dim + 2

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "emb",
                self.emb_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32)
                    * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            )
        ]

    def dense_init(self, rng: jax.Array) -> dict:
        ku, ki = jax.random.split(rng)
        user_in = self.split_field * self.emb_dim
        item_in = (self.max_fields - self.split_field) * self.emb_dim
        # + 1 output lane per tower: the per-side bias the dot
        # augmentation folds in (module docstring)
        dense = mlp_tower_init(
            ku, user_in, self.hidden, self.tower_dim + 1, prefix="u_"
        )
        dense.update(mlp_tower_init(
            ki, item_in, self.hidden, self.tower_dim + 1, prefix="i_"
        ))
        return dense

    def _towers_input(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> jax.Array:
        """One shared field-pool over ALL fields [B, F, E]; each tower
        slices its own field range (one one-hot matmul serves both)."""
        return field_sum_tower(
            rows["emb"], masked_x(batch), batch["slots"], self.max_fields
        )

    def user_embed(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        """[B, tower_dim + 2] augmented user-tower output
        ``[u, b_u, 1]`` — the serve-time query embedding
        (PredictEngine.topk runs exactly this, then a dot scan over
        the frozen item index)."""
        assert dense is not None, "two_tower requires dense tower params"
        part = self._towers_input(rows, batch)[:, : self.split_field]
        m = mlp_tower(dense, part.reshape(part.shape[0], -1), "u_")
        ones = jnp.ones((m.shape[0], 1), m.dtype)
        return jnp.concatenate([m, ones], axis=-1)  # [u, b_u, 1]

    def item_embed(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        """[B, tower_dim + 2] augmented item-tower output
        ``[i, 1, b_i]`` — what export_item_index freezes, one row per
        catalog item (the bias lane IS the item's popularity prior,
        frozen into its index row)."""
        assert dense is not None, "two_tower requires dense tower params"
        part = self._towers_input(rows, batch)[:, self.split_field:]
        m = mlp_tower(dense, part.reshape(part.shape[0], -1), "i_")
        ones = jnp.ones((m.shape[0], 1), m.dtype)
        return jnp.concatenate(
            [m[:, : self.tower_dim], ones, m[:, self.tower_dim:]],
            axis=-1,
        )  # [i, 1, b_i]

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        # training logit == the retrieval score: same dot, so index
        # scores are calibrated against the trained objective
        return dot_interaction(
            self.user_embed(rows, batch, dense),
            self.item_embed(rows, batch, dense),
        )
