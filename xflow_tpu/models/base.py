"""Model protocol: pluggable losses over gathered sparse rows.

A model declares its parameter tables (the reference's "stores": LR
uses store 0 (w) only, FM stores 0+1 (w, v), MVM store 1 (v) only —
server.h:23-28, lr_worker.h:38, fm_worker.h:37-38, mvm_worker.h:38) and
provides, for a batch whose rows are already gathered to [B, K, D]
blocks:

* ``logit(rows, batch) -> [B]`` — the pre-sigmoid score;
* ``grad_logit(rows, batch) -> {table: [B, K, D]}`` — d logit / d row
  entry, per occurrence.

Gradients are explicit, not autodiff, because the reference's FM
backward is *not* the true gradient of its forward (fm_worker.cc:82 vs
:140-142 — the ½ factor is dropped in forward only) and parity requires
reproducing that; see models/fm.py.

The train step turns these into parameter updates:
``g_occurrence = (sigma(logit) - y) * weight / num_real * grad_logit``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Protocol

import jax

# Batch as a jit-friendly pytree: keys/slots/vals/mask [B,K], labels/weights [B].
BatchArrays = dict[str, jax.Array]


@dataclasses.dataclass(frozen=True)
class TableSpec:
    name: str
    dim: int  # row width (1 for w; v_dim for latent factors)
    init: Callable[[jax.Array, tuple[int, int]], jax.Array]  # (rng, shape) -> array
    # Whether this table's hot-plane occurrences ride the two-level
    # one-hot MXU path (ops/hot.py).  The MXU route moves
    # M*(h1 + h2*dim) one-hot elements per M occurrences — a win for
    # narrow rows, but for very wide rows (FFM's v: max_fields*v_dim
    # ≈ 156 lanes) the h2*dim term makes it slower than the ~100 ns
    # DMA descriptor it replaces.  hot=False keeps THIS table's hot
    # occurrences on plain gather/scatter while other tables (and the
    # batch steering/remap) still use the hot machinery — e.g. FFM
    # takes the MXU win on its scalar w and leaves v on DMA, halving
    # its per-occurrence descriptor count.
    hot: bool = True
    # Declarative row-init distribution, the LAZY counterpart of
    # ``init``: the tiered parameter store (store/cold.py) materializes
    # a row only when it is first touched, so the initial value of row
    # r must be computable per-row, deterministically, and independent
    # of the table size — a [T, D] init draw is exactly the full-table
    # materialization the store exists to avoid at T=2^28.
    # "zeros" covers w tables; "normal" is N(0,1)*init_scale per entry
    # (the reference's lazy server-side v init, ftrl.h:113-120 — which
    # was itself per-row-on-first-touch, so the store reproduces the
    # REFERENCE semantics more literally than the eager ``init`` does).
    init_kind: str = "zeros"  # {"zeros", "normal"}
    init_scale: float = 0.0


class Model(Protocol):
    name: str

    def tables(self) -> list[TableSpec]:
        ...

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        ...

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        ...


class AutodiffModel:
    """Base for models without reference gradient quirks (FFM,
    wide&deep): define ``logit`` only — the train step derives
    per-occurrence table gradients and dense-parameter gradients with
    jax.grad.  May also own dense (non-table, replicated) parameters,
    e.g. MLP weights, via ``dense_init``."""

    #: marker the train step dispatches on
    autodiff = True

    def dense_init(self, rng: jax.Array) -> dict:
        """Replicated dense parameter pytree ({} if none)."""
        return {}

    def logit(
        self,
        rows: dict[str, jax.Array],
        batch: BatchArrays,
        dense: dict | None = None,
    ) -> jax.Array:
        raise NotImplementedError
