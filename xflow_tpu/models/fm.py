"""2-way Factorization Machine (reference: src/model/fm/fm_worker.{h,cc}).

Forward (fm_worker.cc:63-86):

    logit = sum_i w_i x_i + sum_d [ (sum_i v_id x_i)^2 - sum_i v_id^2 x_i^2 ]

Note the standard FM ½ factor on the interaction term is **absent** in
the reference forward (fm_worker.cc:82,86) — reproduced here.

Backward (fm_worker.cc:140-142): grad_w_i = 1, grad_v_id =
(sum_j v_jd x_j - v_id x_i) * x_i — i.e. the gradient of the *½-scaled*
forward.  The forward/backward pair is therefore inconsistent by a
factor of 2 on the interaction term; this is reference semantics and is
reproduced exactly (and why grads here are explicit, not autodiff).

v rows are initialized N(0,1)*1e-2 (the reference does this lazily
server-side on first touch, ftrl.h:113-120; see optim/ftrl.py for the
equivalence argument), laid out [key, d in 0..v_dim) as in
fm_worker.cc:71.

Expressed through models/blocks.py (masked_x / linear_term /
fm_pair_pieces) — bitwise-unchanged vs the pre-refactor forms
(tests/test_models.py no-regression pins).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import BatchArrays, TableSpec
from xflow_tpu.models.blocks import fm_pair_pieces, linear_term, masked_x


@dataclasses.dataclass(frozen=True)
class FMModel:
    v_dim: int = 10  # reference: ftrl.h:16
    v_init_scale: float = 1e-2
    name: str = "fm"
    # never reads batch["slots"] (the 2-way interaction sums over ALL
    # features, fm_worker.cc:63-86) — compact-wire eligible (step.py)
    uses_slots = False

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec("w", 1, lambda rng, shape: jnp.zeros(shape, jnp.float32)),
            TableSpec(
                "v",
                self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            ),
        ]

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        x = masked_x(batch)
        linear = linear_term(rows["w"], x)
        sum_vx, sum_vx2 = fm_pair_pieces(rows["v"], x)
        # No ½ factor: fm_worker.cc:82,86.
        return linear + jnp.sum(sum_vx * sum_vx - sum_vx2, axis=-1)

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = masked_x(batch)  # [B, K]
        sum_vx, _ = fm_pair_pieces(rows["v"], x)
        vx = rows["v"] * x[..., None]
        # (sum_vx - v_id x_i) * x_i — fm_worker.cc:140-142 (½-scaled form).
        grad_v = (sum_vx[:, None, :] - vx) * x[..., None]
        return {"w": x[..., None], "v": grad_v}
