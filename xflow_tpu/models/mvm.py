"""Multi-View Machine (reference: src/model/mvm/mvm_worker.{h,cc}).

Per factor d, the reference sums v within each field (fgid/slot), then
multiplies across fields, then sums over factors (mvm_worker.cc:67-95):

    reference forward:  logit = sum_d  prod_s ( sum_{i in s} v_id )
    reference backward: grad_v_id = prod_s(...) / (1 + slotsum_{s(i),d})
                        (0 when the slot sum is 0, mvm_worker.cc:155-156)

The reference's forward multiplies the bare slot sum but its backward
divides by (1 + slot sum) — a forward/backward mismatch flagged in the
SURVEY quirks ledger with the recommendation to fix both sides to the
``1 + sum`` form (which also matches the MVM paper's view-augmentation
with a constant-1 feature, and makes empty fields contribute a neutral
factor 1).  We implement the fixed, consistent form, CENTERED:

    logit = sum_d [ prod_s (1 + slotsum_sd)  -  1 ]
    grad_v_id = x_i * prod_s(1 + slotsum_sd) / (1 + slotsum_{s(i),d})

The ``- 1`` per factor removes the structural baseline: at init every
slotsum is ~0, so the uncentered product is ~1 per factor and the logit
starts at +v_dim (sigmoid ~0.9999) — measured on the convergence
dataset, the uncentered form spends its first epochs burning that bias
down (test logloss 0.70 after an epoch vs 0.58 base rate) instead of
learning.  The shift is a constant, so gradients are identical; it is
exactly a fixed -v_dim bias.  (The reference's bare-product forward has
the opposite degeneracy: products of ~N(0, 1e-2) slot sums vanish to
~0 and freeze MVM at sigma(0)=0.5 with ~0 gradients.)

This is the one intentional numeric divergence from the reference for
MVM; documented here and exercised in tests/test_models.py.

Field handling: the reference sizes per-sample slot arrays from the max
fgid seen (mvm_worker.cc:225-243); under static shapes fields are fixed
to ``max_fields`` and features with fgid >= max_fields are ignored
(config.max_fields).  MVM uses only the v table (store 1,
mvm_worker.h:38); v rows init N(0,1)*1e-2 like FM.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import BatchArrays, TableSpec
from xflow_tpu.models.blocks import masked_x, mvm_slot_terms

_GUARD_EPS = 1e-12


@dataclasses.dataclass(frozen=True)
class MVMModel:
    v_dim: int = 10
    v_init_scale: float = 1e-2
    max_fields: int = 32
    name: str = "mvm"

    def tables(self) -> list[TableSpec]:
        return [
            TableSpec(
                "v",
                self.v_dim,
                lambda rng, shape: (
                    jax.random.normal(rng, shape, jnp.float32) * self.v_init_scale
                ),
                init_kind="normal",
                init_scale=self.v_init_scale,
            )
        ]

    def _slot_terms(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> tuple[jax.Array, jax.Array]:
        """Returns (one_plus_slotsum [B, S, D], prod over S [B, D]) —
        blocks.mvm_slot_terms, bitwise the pre-refactor expression."""
        return mvm_slot_terms(
            rows["v"], masked_x(batch), batch["slots"], self.max_fields
        )

    def logit(self, rows: dict[str, jax.Array], batch: BatchArrays) -> jax.Array:
        _, prod = self._slot_terms(rows, batch)
        # centered: remove the structural +v_dim baseline (docstring)
        return jnp.sum(prod - 1.0, axis=-1)

    def grad_logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        x = masked_x(batch)  # [B, K]
        one_plus, prod = self._slot_terms(rows, batch)
        slot_idx = jnp.clip(batch["slots"], 0, self.max_fields - 1)  # [B, K]
        own = jnp.take_along_axis(
            one_plus,
            slot_idx[:, :, None],  # [B, K, 1] indexing axis 1 (S); broadcasts over D
            axis=1,
        )  # [B, K, D]
        safe = jnp.where(jnp.abs(own) < _GUARD_EPS, 1.0, own)
        grad_v = jnp.where(
            jnp.abs(own) < _GUARD_EPS,
            0.0,  # guard mirrors the reference zeroing at mvm_worker.cc:156
            prod[:, None, :] / safe,
        ) * x[..., None]
        # match the forward's one-hot semantics exactly: slots outside
        # [0, max_fields) contribute nothing there (zero one-hot row),
        # so they must get zero gradient here too — without the >= 0
        # arm, a negative slot was ignored in the forward but trained
        # as field 0 (the clip above) in the backward
        valid = (
            (batch["slots"] >= 0) & (batch["slots"] < self.max_fields)
        )[..., None]
        return {"v": jnp.where(valid, grad_v, 0.0)}
