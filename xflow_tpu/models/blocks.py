"""Composable model blocks — embedding towers + interaction blocks.

Five CTR families grew up in ``models/`` each re-implementing the same
sparse recipe: mask the values, gather rows, pool per field, interact,
reduce.  This module is the single home of those pieces, used three
ways:

* the five incumbent families (lr/fm/mvm/ffm/wide_deep) express their
  logits THROUGH these blocks — with **bitwise-unchanged** outputs
  (tests/test_models.py pins every family's logit against a frozen
  copy of the pre-refactor implementation, in dense, MXU-hot, and
  tiered store modes);
* the retrieval/ranking families this substrate enables
  (models/two_tower.py, models/dcn.py) compose the same blocks into
  new architectures instead of re-implementing the recipe a sixth and
  seventh time;
* future families register in models/__init__.py and pick blocks off
  this shelf.

Bitwise discipline: each block body is the EXACT expression lifted
from the incumbent model it came from (same ops, same order, same
einsum strings).  A change here is a numerics change for every family
at once — the no-regression tests exist to catch exactly that.

Blocks take gathered rows / already-masked values, never a model
instance: they are jit-safe pure functions over arrays, so any model's
``logit`` (and explicit ``grad_logit`` where the reference demands
quirk parity) can call them inside the fused step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from xflow_tpu.models.base import BatchArrays

# -- feature plumbing ---------------------------------------------------------


def masked_x(batch: BatchArrays) -> jax.Array:
    """Effective feature values: ``vals * mask`` [B, K] — zero for
    padding, the value (1.0 in hash mode) for real entries.  Every
    family's first line."""
    return batch["vals"] * batch["mask"]


def linear_term(w_rows: jax.Array, x: jax.Array) -> jax.Array:
    """Sparse linear reduction ``sum_i w_i x_i`` [B] over gathered
    [B, K, 1] w rows (lr_worker.cc:121-143's join as a masked gather
    reduction) — LR's whole forward, FM/FFM/wide&deep/DCN's wide
    half."""
    return jnp.sum(w_rows[..., 0] * x, axis=-1)


def valid_fields(
    slots: jax.Array, mask: jax.Array, num_fields: int
) -> jax.Array:
    """Bool [B, K]: the entry is real AND its field id is in
    [0, num_fields) — the shared out-of-range-field drop semantics
    (negative or oversized fgids contribute nothing; mvm.py / ffm.py /
    wide&deep's one-hot rows of zeros)."""
    return (slots >= 0) & (slots < num_fields) & (mask > 0)


# -- embedding tower ----------------------------------------------------------


def field_sum_tower(
    emb_rows: jax.Array,
    x: jax.Array,
    slots: jax.Array,
    num_fields: int,
) -> jax.Array:
    """THE embedding tower: value-scaled embeddings field-sum-pooled
    into ``num_fields`` buckets — [B, F, E] from gathered [B, K, E]
    rows.  One one-hot + one MXU batch-matmul, so variable
    features-per-field work under static shapes; out-of-range fields
    get an all-zero one-hot row and drop out.  Lifted verbatim from
    wide&deep's deep half; two_tower and dcn build their towers on
    it."""
    onehot = jax.nn.one_hot(
        slots, num_fields, dtype=x.dtype
    )  # [B, K, F]; out-of-range fields drop out
    embx = emb_rows * x[..., None]  # [B, K, E]
    return jnp.einsum("bkf,bke->bfe", onehot, embx)  # [B, F, E]


def flatten_tower(field_emb: jax.Array) -> jax.Array:
    """[B, F, E] -> [B, F*E]: the tower's dense-layer interface."""
    return field_emb.reshape(field_emb.shape[0], -1)


# -- MLP blocks (replicated dense params; plain-SGD updated — see
# parallel/step.py::apply_dense_sgd) -----------------------------------------


def mlp_head_init(
    rng: jax.Array, in_dim: int, hidden: int
) -> dict[str, jax.Array]:
    """He-init 2-layer scalar head (wide&deep's exact dense geometry):
    in_dim -> hidden (ReLU) -> 1."""
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (in_dim, hidden), jnp.float32)
        * jnp.sqrt(2.0 / in_dim),
        "b1": jnp.zeros((hidden,), jnp.float32),
        "w2": jax.random.normal(k2, (hidden, 1), jnp.float32)
        * jnp.sqrt(1.0 / hidden),
        "b2": jnp.zeros((1,), jnp.float32),
    }


def mlp_head(dense: dict, h: jax.Array) -> jax.Array:
    """2-layer ReLU scalar head -> [B] (wide&deep's deep output,
    verbatim)."""
    h = jax.nn.relu(h @ dense["w1"] + dense["b1"])
    return (h @ dense["w2"] + dense["b2"])[:, 0]


def mlp_tower_init(
    rng: jax.Array, in_dim: int, hidden: int, out_dim: int,
    prefix: str = "",
) -> dict[str, jax.Array]:
    """He-init 2-layer VECTOR tower: in_dim -> hidden (ReLU) ->
    out_dim, keys prefixed so two towers coexist in one dense pytree
    (two_tower's u_/i_ pair)."""
    k1, k2 = jax.random.split(rng)
    return {
        f"{prefix}w1": jax.random.normal(
            k1, (in_dim, hidden), jnp.float32
        ) * jnp.sqrt(2.0 / in_dim),
        f"{prefix}b1": jnp.zeros((hidden,), jnp.float32),
        f"{prefix}w2": jax.random.normal(
            k2, (hidden, out_dim), jnp.float32
        ) * jnp.sqrt(1.0 / hidden),
        f"{prefix}b2": jnp.zeros((out_dim,), jnp.float32),
    }


def mlp_tower(dense: dict, h: jax.Array, prefix: str = "") -> jax.Array:
    """2-layer ReLU vector tower -> [B, out_dim]."""
    h = jax.nn.relu(h @ dense[f"{prefix}w1"] + dense[f"{prefix}b1"])
    return h @ dense[f"{prefix}w2"] + dense[f"{prefix}b2"]


def dot_interaction(u: jax.Array, v: jax.Array) -> jax.Array:
    """Row-wise dot product [B] of two [B, D] tower outputs — the
    two-tower training logit AND the serve-time top-k score (the
    index scan is the same dot against every item row)."""
    return jnp.sum(u * v, axis=-1)


def cross_network(
    x0: jax.Array, cross_w: jax.Array, cross_b: jax.Array
) -> jax.Array:
    """DCN explicit cross stack: ``x_{l+1} = x0 * (x_l . w_l) + b_l +
    x_l`` over ``cross_w [L, P]`` / ``cross_b [L, P]`` — each layer
    adds one learned degree of bounded polynomial feature interaction
    at O(P) parameters (vs the MLP's O(P*H))."""
    x = x0
    for layer in range(cross_w.shape[0]):
        xw = jnp.sum(x * cross_w[layer], axis=-1, keepdims=True)  # [B, 1]
        x = x0 * xw + cross_b[layer] + x
    return x


# -- factorization interactions ----------------------------------------------


def fm_pair_pieces(
    v_rows: jax.Array, x: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """FM second-order pieces over gathered [B, K, D] v rows:
    ``(sum_i v_i x_i, sum_i (v_i x_i)^2)`` both [B, D]
    (fm_worker.cc:63-86's square-of-sum/sum-of-squares identity).
    The forward combines them WITHOUT the standard ½ factor (reference
    quirk, models/fm.py docstring); the backward reads sum_vx
    directly."""
    vx = v_rows * x[..., None]  # [B, K, D]
    sum_vx = jnp.sum(vx, axis=1)  # [B, D]
    sum_vx2 = jnp.sum(vx * vx, axis=1)  # [B, D]
    return sum_vx, sum_vx2


def mvm_slot_terms(
    v_rows: jax.Array,
    x: jax.Array,
    slots: jax.Array,
    num_fields: int,
) -> tuple[jax.Array, jax.Array]:
    """MVM per-factor view products: ``(1 + slotsum [B, S, D],
    prod over S [B, D])`` in the fixed consistent 1+sum form
    (models/mvm.py docstring; mvm_worker.cc:67-95)."""
    onehot = jax.nn.one_hot(
        slots, num_fields, dtype=x.dtype
    )  # [B, K, S]; fgid >= num_fields rows are all-zero → feature ignored
    vx = v_rows * x[..., None]  # [B, K, D]
    slotsum = jnp.einsum("bks,bkd->bsd", onehot, vx)  # [B, S, D]
    one_plus = 1.0 + slotsum
    prod = jnp.prod(one_plus, axis=1)  # [B, D]
    return one_plus, prod


def ffm_field_interaction(
    v_rows: jax.Array,
    x_eff: jax.Array,
    slot: jax.Array,
    valid: jax.Array,
    num_fields: int,
    v_dim: int,
) -> jax.Array:
    """FFM pairwise term via the field-aggregated identity
    (models/ffm.py docstring: O(B*K*F^2*D) MXU compute, O(B*F^2*D)
    memory, no [B, K, K, D] pair tensors).  ``v_rows`` is the flat
    [B, K, F*D] gathered v plane, ``x_eff`` the validity-zeroed
    values, ``slot`` the [0, F)-clipped field ids.  Returns the [B]
    interaction (½(cross − diag)); the TPU layout constraints
    (E = F*D stays the minor dim throughout) ride along unchanged."""
    b, k = slot.shape
    f, d = num_fields, v_dim
    # one-hot of each feature's own field; zero row for invalid
    onehot = (
        (slot[:, :, None] == jnp.arange(f)[None, None, :])
        & valid[:, :, None]
    ).astype(v_rows.dtype)  # [B, K, F]

    # TPU layout constraint: every materialized tensor keeps the
    # flattened E = F*D as its minor dimension (models/ffm.py round-4
    # log: a D-minor operand gets T(8,128) lane padding — 32x memory)
    vx = v_rows * x_eff[:, :, None]  # [B, K, E]
    # field-aggregated sums: one batch matmul contracting K (MXU)
    s = jnp.einsum("bkf,bke->bfe", onehot, vx)  # [B, F, E]

    # cross term sum_{f1,f2,d} S[b,f1,f2,d] * S[b,f2,f1,d]: stays an
    # elementwise fusion over s read twice — never a dot_general
    s4 = s.reshape(b, f, f, d)
    cross = jnp.sum(
        s4 * jnp.transpose(s4, (0, 2, 1, 3)), axis=(1, 2, 3)
    )
    # subtract the i == i diagonal: x_i^2 * ||v[k_i, f_i, :]||^2,
    # selecting each key's own-field block of E elementwise
    eslot = (jnp.arange(f * d) // d).astype(slot.dtype)  # [E]
    emask = eslot[None, None, :] == slot[:, :, None]  # [B, K, E]
    diag = jnp.sum(jnp.where(emask, vx * vx, 0.0), axis=(1, 2))
    return 0.5 * (cross - diag)
