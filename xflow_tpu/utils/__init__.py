from xflow_tpu.utils.metrics import (
    sigmoid_ref,
    logloss,
    auc_midrank,
    auc_rank_sum,
    AucAccumulator,
)

__all__ = [
    "sigmoid_ref",
    "logloss",
    "auc_midrank",
    "auc_rank_sum",
    "AucAccumulator",
]
