"""Checkpoint / resume — a capability gap the reference lacks entirely
(SURVEY §5: weights live only in server RAM; training ends, weights
vanish).  Here: atomic directory checkpoints holding every table array
(param + optimizer state, e.g. FTRL n/z), the step counter, and a JSON
manifest with the data cursor (epoch, shard index, byte offset) so
training resumes mid-shard at block granularity.

Format: plain .npy per array + manifest.json, written to a temp dir and
renamed — no dependency on orbax so the format stays trivially
inspectable and portable.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any

import numpy as np

import jax

MANIFEST = "manifest.json"


def save_checkpoint(
    directory: str,
    state: dict[str, Any],
    cursor: dict[str, Any],
    config_json: str | None = None,
) -> str:
    """Write one checkpoint; returns its path.  ``state`` is the train
    step's pytree; ``cursor`` is loader position metadata."""
    step = int(jax.device_get(state["step"]))
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"ckpt-{step:010d}")
    tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=directory)
    try:
        arrays: dict[str, str] = {}
        for tname, table in state["tables"].items():
            for aname, arr in table.items():
                fname = f"{tname}.{aname}.npy"
                np.save(os.path.join(tmp, fname), np.asarray(jax.device_get(arr)))
                arrays[f"{tname}/{aname}"] = fname
        for dname, arr in state.get("dense", {}).items():
            fname = f"dense.{dname}.npy"
            np.save(os.path.join(tmp, fname), np.asarray(jax.device_get(arr)))
            arrays[f"dense/{dname}"] = fname
        manifest = {
            "step": step,
            "arrays": arrays,
            "cursor": cursor,
            "config": config_json,
        }
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _write_latest(directory, os.path.basename(final))
    return final


def _write_latest(directory: str, name: str) -> None:
    tmp = os.path.join(directory, ".latest.tmp")
    with open(tmp, "w") as f:
        f.write(name)
    os.replace(tmp, os.path.join(directory, "LATEST"))


def latest_checkpoint(directory: str) -> str | None:
    marker = os.path.join(directory, "LATEST")
    if os.path.exists(marker):
        with open(marker) as f:
            name = f.read().strip()
        path = os.path.join(directory, name)
        if os.path.exists(path):
            return path
    if not os.path.isdir(directory):
        return None
    cands = sorted(
        d for d in os.listdir(directory) if d.startswith("ckpt-")
    )
    return os.path.join(directory, cands[-1]) if cands else None


def load_checkpoint(
    path: str, state: dict[str, Any]
) -> tuple[dict[str, Any], dict[str, Any]]:
    """Restore into the (freshly initialized, correctly sharded) ``state``
    template; returns (new_state, cursor).  Arrays are device_put with the
    template's sharding, so a checkpoint written on one mesh restores onto
    another (row-sharding is resharded by XLA)."""
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)
    new_tables: dict[str, Any] = {}
    for tname, table in state["tables"].items():
        new_tables[tname] = {}
        for aname, arr in table.items():
            key = f"{tname}/{aname}"
            if key not in manifest["arrays"]:
                raise ValueError(f"checkpoint {path} missing array {key}")
            host = np.load(os.path.join(path, manifest["arrays"][key]))
            if host.shape != arr.shape:
                raise ValueError(
                    f"checkpoint array {key} shape {host.shape} != state {arr.shape}"
                )
            new_tables[tname][aname] = jax.device_put(host, arr.sharding)
    new_dense = {}
    for dname, arr in state.get("dense", {}).items():
        key = f"dense/{dname}"
        if key not in manifest["arrays"]:
            raise ValueError(f"checkpoint {path} missing array {key}")
        host = np.load(os.path.join(path, manifest["arrays"][key]))
        if host.shape != arr.shape:
            raise ValueError(
                f"checkpoint array {key} shape {host.shape} != state {arr.shape}"
            )
        new_dense[dname] = jax.device_put(host, arr.sharding)
    import jax.numpy as jnp

    new_state = {
        "tables": new_tables,
        "dense": new_dense,
        "step": jnp.asarray(manifest["step"], jnp.int32),
    }
    return new_state, manifest["cursor"]
