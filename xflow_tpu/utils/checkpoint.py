"""Checkpoint / resume — a capability gap the reference lacks entirely
(SURVEY §5: weights live only in server RAM; training ends, weights
vanish).  Here: atomic directory checkpoints holding every table array
(param + optimizer state, e.g. FTRL n/z), the step counter, and a JSON
manifest with per-host data cursors (epoch, shard index, byte offset)
so training resumes mid-shard at block granularity.

Sharded I/O (round-2 redesign): each process writes ONLY the table row
ranges its devices own — no allgather, so peak host memory and network
traffic are O(T / num_processes) per process instead of O(T) everywhere
(at the 2^28-row north star with FM that allgather was ~35 GB per
process per checkpoint).  A row-range file is named
``<table>.<array>.r<start>-<stop>.npy``; restore assembles any target
sharding from whichever ranges exist via mmap, so a checkpoint written
on one mesh restores onto another (including different process counts).

Multi-host protocol (shared checkpoint filesystem assumed, the normal
arrangement): all processes write into a deterministic temp dir, a
barrier ensures completeness, then process 0 writes the manifest and
atomically renames.  Format: plain .npy + manifest.json — no orbax
dependency, trivially inspectable.
"""

from __future__ import annotations

import glob
import json
import os
import re
import shutil
from typing import Any

import numpy as np

import jax

from xflow_tpu.chaos import failpoint

MANIFEST = "manifest.json"

_RANGE_RE = re.compile(r"\.r(\d+)-(\d+)\.npy$")


def all_ok(local_ok: bool) -> bool:
    """True iff every process reports success.  Doubles as a barrier, so
    a process that FAILED its local I/O still reaches this point and the
    others learn about the failure instead of deadlocking in a plain
    sync (every code path on every process must call this the same
    number of times).  Public: serve/artifact.py runs the same
    write-shards/vote/finalize protocol for inference artifacts."""
    if jax.process_count() == 1:
        return local_ok
    from jax.experimental import multihost_utils

    flags = np.asarray(
        multihost_utils.process_allgather(np.int32(1 if local_ok else 0))
    )
    return bool(flags.min() == 1)


class IncompatibleCheckpoint(ValueError):
    """Checkpoint exists but cannot be loaded by this version (e.g. a
    pre-sharded-format manifest).  Trainer.restore treats it as
    'no usable checkpoint' rather than crashing."""


def iter_owned_shards(arr: jax.Array):
    """(start_row, stop_row, host_data) for every addressable shard this
    process is responsible for writing (replica 0 of each distinct row
    range — replicated copies on other devices/processes skip).
    Public: shared with serve/artifact.py's export."""
    seen: set[tuple[int, int]] = set()
    nrows = arr.shape[0]
    for shard in arr.addressable_shards:
        idx = shard.index
        rows = idx[0] if idx else slice(None)
        start = rows.start or 0
        stop = rows.stop if rows.stop is not None else nrows
        if len(idx) > 1:
            cols = idx[1]
            if not (cols.start in (None, 0) and cols.stop in (None, arr.shape[1])):
                raise NotImplementedError(
                    "checkpointing assumes column-replicated tables"
                )
        if shard.replica_id != 0 or (start, stop) in seen:
            continue
        seen.add((start, stop))
        yield start, stop, np.asarray(shard.data)


def _flat_arrays(state: dict[str, Any]) -> list[tuple[str, jax.Array]]:
    """(key, array) for every table array, in deterministic order."""
    out = []
    for tname in sorted(state["tables"]):
        for aname in sorted(state["tables"][tname]):
            out.append((f"{tname}.{aname}", state["tables"][tname][aname]))
    return out


def save_checkpoint(
    directory: str,
    state: dict[str, Any],
    cursor: dict[str, Any],
    config_json: str | None = None,
    keep: int = 0,
) -> str:
    """Write one checkpoint; returns its path.  ``state`` is the train
    step's pytree; ``cursor`` is loader-position metadata — pass
    per-host cursors under ``cursor["cursors"]`` (trainer.save does).
    ``keep`` > 0 deletes all but the newest ``keep`` ckpt-* dirs after a
    successful save (0 = keep everything).

    Multi-host: COLLECTIVE — all processes call together; each writes
    its own shards (see module docstring)."""
    step = int(jax.device_get(state["step"]))
    final = os.path.join(directory, f"ckpt-{step:010d}")
    tmp = os.path.join(directory, f".tmp-ckpt-{step:010d}")
    proc = jax.process_index()
    # Every process passes through ALL THREE all_ok gates on every
    # path, so a local I/O failure at any stage — including process 0's
    # mkdir, which runs before any peer has work to do — is reported to
    # the peers instead of leaving them deadlocked (a bare barrier here
    # would hang: the failing process would enter all_ok's allgather
    # while the others sit in sync_global_devices).
    err: BaseException | None = None
    try:
        if proc == 0:
            os.makedirs(directory, exist_ok=True)
            if os.path.exists(tmp):  # leftover from a crashed attempt
                shutil.rmtree(tmp)
            os.makedirs(tmp)
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if err is not None:
            raise err
        raise RuntimeError(
            f"checkpoint mkdir failed on process 0 (step {step})"
        )
    try:
        # chaos site: a fire mid-write takes the all_ok error path —
        # the half-written .tmp dir is cleaned and the previous
        # committed generation stays the newest complete one
        failpoint("ckpt.write_shard")
        arrays_meta: dict[str, Any] = {}
        for key, arr in _flat_arrays(state):
            arrays_meta[key] = {
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
            for start, stop, host_data in iter_owned_shards(arr):
                np.save(
                    os.path.join(tmp, f"{key}.r{start:012d}-{stop:012d}.npy"),
                    host_data,
                )
        if proc == 0:
            for dname in sorted(state.get("dense", {})):
                arr = state["dense"][dname]
                np.save(
                    os.path.join(tmp, f"dense.{dname}.npy"),
                    np.asarray(jax.device_get(arr)),
                )
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if proc == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        if err is not None:
            raise err
        raise RuntimeError(
            f"checkpoint save failed on another process (step {step})"
        )
    try:
        if proc == 0:
            manifest = {
                "format": 2,
                "step": step,
                "arrays": arrays_meta,
                "dense": sorted(state.get("dense", {})),
                "cursor": cursor,
                "config": config_json,
            }
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f, indent=2)
            # chaos site: a "kill mid-commit" — the manifest is written
            # (inside tmp; manifest-last ordering means no final dir
            # ever exists without one) but the rename never runs, so
            # the generation is invisible to latest_complete()
            failpoint("ckpt.finalize")
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(tmp, final)
            _write_latest(directory, os.path.basename(final))
            if keep > 0:
                gc_checkpoints(directory, keep)
    except BaseException as e:
        err = e
    if not all_ok(err is None):
        if proc == 0:
            shutil.rmtree(tmp, ignore_errors=True)
        if err is not None:
            raise err
        raise RuntimeError(
            f"checkpoint finalize failed on process 0 (step {step})"
        )
    return final


def gc_checkpoints(directory: str, keep: int) -> list[str]:
    """Delete all but the newest ``keep`` COMPLETE ckpt-* dirs (by
    step number — the zero-padded name sorts chronologically); returns
    the deleted paths.  Only complete generations (manifest present)
    count toward the keep budget and only they are deleted: a
    manifest-less dir is external corruption, not a generation — it
    must neither occupy a keep slot (which would leave fewer than
    ``keep`` restorable generations for ``--resume auto``) nor be
    silently destroyed (it is evidence).  The dir LATEST points at is
    never deleted even if a clock anomaly makes it sort old.
    Process-0-only in multi-host runs (save_checkpoint calls it inside
    the rank-0 finalize block)."""
    assert keep > 0
    cands = sorted(
        d
        for d in os.listdir(directory)
        if d.startswith("ckpt-")
        and os.path.isdir(os.path.join(directory, d))
        and is_complete(os.path.join(directory, d))
    )
    latest = None
    marker = os.path.join(directory, "LATEST")
    if os.path.exists(marker):
        with open(marker) as f:
            latest = f.read().strip()
    doomed = [d for d in cands[:-keep] if d != latest]
    removed = []
    for d in doomed:
        path = os.path.join(directory, d)
        shutil.rmtree(path, ignore_errors=True)
        removed.append(path)
    return removed


def _write_latest(directory: str, name: str) -> None:
    tmp = os.path.join(directory, ".latest.tmp")
    with open(tmp, "w") as f:
        f.write(name)
    os.replace(tmp, os.path.join(directory, "LATEST"))


def latest_checkpoint(directory: str) -> str | None:
    marker = os.path.join(directory, "LATEST")
    if os.path.exists(marker):
        # metadata peek: a torn/missing marker falls through to the
        # directory scan; restore itself carries ckpt.restore
        # (xf: ignore[XF018])
        with open(marker) as f:
            name = f.read().strip()
        path = os.path.join(directory, name)
        if os.path.exists(path):
            return path
    if not os.path.isdir(directory):
        return None
    cands = sorted(
        d for d in os.listdir(directory) if d.startswith("ckpt-")
    )
    return os.path.join(directory, cands[-1]) if cands else None


def checkpoint_candidates(directory: str) -> list[str]:
    """Every ckpt-* generation path, NEWEST first (zero-padded step in
    the name sorts chronologically).  .tmp-ckpt-* leftovers from
    crashed saves are never candidates."""
    if not os.path.isdir(directory):
        return []
    cands = sorted(
        (
            d
            for d in os.listdir(directory)
            if d.startswith("ckpt-")
            and os.path.isdir(os.path.join(directory, d))
        ),
        reverse=True,
    )
    return [os.path.join(directory, d) for d in cands]


def is_complete(path: str) -> bool:
    """A generation is COMPLETE iff its manifest exists — the commit
    protocol writes the manifest into the tmp dir and renames last, so
    a committed generation always has one; a manifest-less ckpt-* dir
    is external corruption (truncated copy, partial delete)."""
    return os.path.exists(os.path.join(path, MANIFEST))


def latest_complete(directory: str) -> str | None:
    """Newest COMPLETE generation, ignoring the LATEST marker (which a
    crash or external tamper can leave stale/corrupt) — the fallback
    `--resume auto` restores from after a kill mid-checkpoint
    (docs/ROBUSTNESS.md)."""
    for path in checkpoint_candidates(directory):
        if is_complete(path):
            return path
    return None


class RangeReader:
    """Assembles arbitrary row/col slices of one array from its
    row-range .npy files via mmap — peak memory O(requested slice)."""

    def __init__(self, path: str, key: str, shape, dtype):
        self.files: list[tuple[int, int, str]] = []
        for f in sorted(glob.glob(os.path.join(path, glob.escape(key) + ".r*.npy"))):
            m = _RANGE_RE.search(f)
            if m:
                self.files.append((int(m.group(1)), int(m.group(2)), f))
        self.files.sort()
        covered = 0
        for start, stop, _ in self.files:
            if start > covered:
                break
            covered = max(covered, stop)
        if covered < shape[0]:
            raise ValueError(
                f"checkpoint {path}: array {key} rows [{covered}, {shape[0]}) "
                f"missing (found {len(self.files)} range files)"
            )
        self.shape = tuple(shape)
        self.dtype = dtype

    def read(self, idx: tuple) -> np.ndarray:
        # chaos site: per-shard mmap read fault during restore/artifact
        # load — distinct from ckpt.restore so mid-assembly faults are
        # injectable (XF018)
        failpoint("ckpt.read_shard")
        rows = idx[0] if idx else slice(None)
        a = rows.start or 0
        b = rows.stop if rows.stop is not None else self.shape[0]
        out = np.empty((b - a, *self.shape[1:]), dtype=self.dtype)
        for start, stop, fname in self.files:
            lo, hi = max(a, start), min(b, stop)
            if lo >= hi:
                continue
            data = np.load(fname, mmap_mode="r")
            out[lo - a : hi - a] = data[lo - start : hi - start]
        if len(idx) > 1 and idx[1] != slice(None):
            out = out[:, idx[1]]
        return out


def load_checkpoint(
    path: str, state: dict[str, Any]
) -> tuple[dict[str, Any], dict[str, Any]]:
    """Restore into the (freshly initialized, correctly sharded) ``state``
    template; returns (new_state, cursor).  Each process reads only the
    row ranges its devices need (mmap), so restore memory is
    O(addressable rows), not O(T)."""
    failpoint("ckpt.restore")
    if not is_complete(path):
        # refuse, don't crash mid-load: a manifest-less generation is
        # an incomplete/corrupt commit — Trainer.restore treats this
        # as "try the next newest complete generation" (auto mode) or
        # "no usable checkpoint" rather than a FileNotFoundError
        raise IncompatibleCheckpoint(
            f"checkpoint {path} has no {MANIFEST} — incomplete or "
            "externally corrupted generation (the commit protocol "
            "writes the manifest before the rename, so this was never "
            "fully committed)"
        )
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("format") != 2:
        raise IncompatibleCheckpoint(
            f"checkpoint {path} has unsupported format "
            f"{manifest.get('format')!r} (expected 2)"
        )
    if "store" in manifest:
        raise IncompatibleCheckpoint(
            f"checkpoint {path} was written by store_mode='tiered' "
            "(tier-erased fold; store/tiered.py) — set "
            "store_mode='tiered' to restore it"
        )

    new_tables: dict[str, Any] = {}
    for tname, table in state["tables"].items():
        new_tables[tname] = {}
        for aname, arr in table.items():
            key = f"{tname}.{aname}"
            meta = manifest["arrays"].get(key)
            if meta is None:
                raise ValueError(f"checkpoint {path} missing array {key}")
            if tuple(meta["shape"]) != arr.shape:
                raise ValueError(
                    f"checkpoint array {key} shape {tuple(meta['shape'])} "
                    f"!= state {arr.shape}"
                )
            reader = RangeReader(path, key, arr.shape, np.dtype(meta["dtype"]))
            new_tables[tname][aname] = jax.make_array_from_callback(
                arr.shape, arr.sharding, reader.read
            )
    new_dense: dict[str, Any] = {}
    for dname, arr in state.get("dense", {}).items():
        fname = os.path.join(path, f"dense.{dname}.npy")
        if not os.path.exists(fname):
            raise ValueError(f"checkpoint {path} missing dense array {dname}")
        host = np.load(fname)
        if host.shape != arr.shape:
            raise ValueError(
                f"checkpoint dense {dname} shape {host.shape} != {arr.shape}"
            )
        new_dense[dname] = jax.device_put(host, arr.sharding)
    import jax.numpy as jnp

    new_state = {
        "tables": new_tables,
        "dense": new_dense,
        "step": jnp.asarray(manifest["step"], jnp.int32),
    }
    return new_state, manifest["cursor"]
