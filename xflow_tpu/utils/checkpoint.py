"""Checkpoint / resume — a capability gap the reference lacks entirely
(SURVEY §5: weights live only in server RAM; training ends, weights
vanish).  Here: atomic directory checkpoints holding every table array
(param + optimizer state, e.g. FTRL n/z), the step counter, and a JSON
manifest with the data cursor (epoch, shard index, byte offset) so
training resumes mid-shard at block granularity.

Format: plain .npy per array + manifest.json, written to a temp dir and
renamed — no dependency on orbax so the format stays trivially
inspectable and portable.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any

import numpy as np

import jax

MANIFEST = "manifest.json"


def _to_host(arr) -> np.ndarray:
    """Materialize a (possibly multi-host-sharded) array on this host.
    COLLECTIVE in multi-process runs — every process must call it for
    every array in the same order."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
    return np.asarray(jax.device_get(arr))


def save_checkpoint(
    directory: str,
    state: dict[str, Any],
    cursor: dict[str, Any],
    config_json: str | None = None,
) -> str:
    """Write one checkpoint; returns its path.  ``state`` is the train
    step's pytree; ``cursor`` is loader position metadata.

    Multi-host: COLLECTIVE — all processes must call it together (the
    sharded tables are allgathered); process 0 writes the files (the
    checkpoint directory is assumed shared or only rank 0's artifacts
    are used, matching rank-0-only artifact conventions elsewhere)."""
    step = int(jax.device_get(state["step"]))
    final = os.path.join(directory, f"ckpt-{step:010d}")
    # materialize first (collective section — identical order everywhere)
    items: list[tuple[str, str, np.ndarray]] = []
    for tname, table in state["tables"].items():
        for aname, arr in table.items():
            items.append((f"{tname}.{aname}.npy", f"{tname}/{aname}", _to_host(arr)))
    for dname, arr in state.get("dense", {}).items():
        items.append((f"dense.{dname}.npy", f"dense/{dname}", _to_host(arr)))
    if jax.process_index() != 0:
        return final
    os.makedirs(directory, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".tmp-ckpt-", dir=directory)
    try:
        arrays: dict[str, str] = {}
        for fname, key, host_arr in items:
            np.save(os.path.join(tmp, fname), host_arr)
            arrays[key] = fname
        manifest = {
            "step": step,
            "arrays": arrays,
            "cursor": cursor,
            "config": config_json,
        }
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=2)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    _write_latest(directory, os.path.basename(final))
    return final


def _write_latest(directory: str, name: str) -> None:
    tmp = os.path.join(directory, ".latest.tmp")
    with open(tmp, "w") as f:
        f.write(name)
    os.replace(tmp, os.path.join(directory, "LATEST"))


def latest_checkpoint(directory: str) -> str | None:
    marker = os.path.join(directory, "LATEST")
    if os.path.exists(marker):
        with open(marker) as f:
            name = f.read().strip()
        path = os.path.join(directory, name)
        if os.path.exists(path):
            return path
    if not os.path.isdir(directory):
        return None
    cands = sorted(
        d for d in os.listdir(directory) if d.startswith("ckpt-")
    )
    return os.path.join(directory, cands[-1]) if cands else None


def load_checkpoint(
    path: str, state: dict[str, Any]
) -> tuple[dict[str, Any], dict[str, Any]]:
    """Restore into the (freshly initialized, correctly sharded) ``state``
    template; returns (new_state, cursor).  Arrays are device_put with the
    template's sharding, so a checkpoint written on one mesh restores onto
    another (row-sharding is resharded by XLA)."""
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)

    def restore_one(key: str, arr):
        if key not in manifest["arrays"]:
            raise ValueError(f"checkpoint {path} missing array {key}")
        host = np.load(os.path.join(path, manifest["arrays"][key]))
        if host.shape != arr.shape:
            raise ValueError(
                f"checkpoint array {key} shape {host.shape} != state {arr.shape}"
            )
        # each process feeds only its addressable shards from the full
        # host copy — works for single-host and multi-host meshes alike
        return jax.make_array_from_callback(
            host.shape, arr.sharding, lambda idx: host[idx]
        )

    new_tables: dict[str, Any] = {}
    for tname, table in state["tables"].items():
        new_tables[tname] = {
            aname: restore_one(f"{tname}/{aname}", arr)
            for aname, arr in table.items()
        }
    new_dense = {
        dname: restore_one(f"dense/{dname}", arr)
        for dname, arr in state.get("dense", {}).items()
    }
    import jax.numpy as jnp

    new_state = {
        "tables": new_tables,
        "dense": new_dense,
        "step": jnp.asarray(manifest["step"], jnp.int32),
    }
    return new_state, manifest["cursor"]
