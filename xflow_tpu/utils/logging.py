"""Structured metrics logging — a SURVEY §5 observability gap filled.

The reference logs to stdout only (rank banner, epoch every 30, final
logloss/AUC — lr_worker.cc:202,209, base.h:101-108).  Here every epoch
and eval emits a JSON line with a monotonic timestamp so runs are
machine-comparable; stdout keeps the human-readable reference-style
lines.

The file opens in APPEND mode (a preempted run resumed with --resume
keeps one history), so every open stamps a ``run_start`` header row —
run id, config digest, rank, host count — and ``python -m xflow_tpu.obs
summarize`` splits runs on it instead of silently merging them.  The
full record schema lives in obs/schema.py (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, IO


class MetricsLogger:
    """Thread-safe: the trainer logs from the main thread while a
    MicroBatcher flushes serve_stats from its worker thread into the
    same file — one lock serializes the closed-check + write so lines
    never interleave and a log racing close() can't hit a closed file
    (XF003 discipline: every ``closed``/file mutation under ``_lock``).
    """

    def __init__(self, path: str, run_header: dict[str, Any] | None = None):
        self._lock = threading.Lock()
        self._f: IO[str] = open(path, "a", buffering=1)
        self._t0 = time.time()
        self.closed = False
        if run_header is not None:
            import os
            import socket

            # hostname/pid stamped HERE so every emitter (trainer,
            # serve bench, smoke scripts) gets them for free — `obs
            # merge`/`doctor` label hosts in multi-host runs by them
            header = {
                "time_unix": round(self._t0, 3),
                "hostname": socket.gethostname(),
                "pid": os.getpid(),
            }
            header.update(run_header)
            self.log("run_start", header)

    def log(self, kind: str, record: dict[str, Any]) -> None:
        row = {"t": round(time.time() - self._t0, 3), "kind": kind}
        row.update(record)
        line = json.dumps(row, sort_keys=True) + "\n"
        with self._lock:
            if self.closed:  # late log after a preemption/exception close
                return
            self._f.write(line)

    def close(self) -> None:
        with self._lock:
            if not self.closed:
                self.closed = True
                self._f.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
