"""Structured metrics logging — a SURVEY §5 observability gap filled.

The reference logs to stdout only (rank banner, epoch every 30, final
logloss/AUC — lr_worker.cc:202,209, base.h:101-108).  Here every epoch
and eval emits a JSON line with a monotonic timestamp so runs are
machine-comparable; stdout keeps the human-readable reference-style
lines.

The file opens in APPEND mode (a preempted run resumed with --resume
keeps one history), so every open stamps a ``run_start`` header row —
run id, config digest, rank, host count — and ``python -m xflow_tpu.obs
summarize`` splits runs on it instead of silently merging them.  The
full record schema lives in obs/schema.py (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import time
from typing import Any, IO


class MetricsLogger:
    def __init__(self, path: str, run_header: dict[str, Any] | None = None):
        self._f: IO[str] = open(path, "a", buffering=1)
        self._t0 = time.time()
        self.closed = False
        if run_header is not None:
            header = {"time_unix": round(self._t0, 3)}
            header.update(run_header)
            self.log("run_start", header)

    def log(self, kind: str, record: dict[str, Any]) -> None:
        if self.closed:  # late log after a preemption/exception close
            return
        row = {"t": round(time.time() - self._t0, 3), "kind": kind}
        row.update(record)
        self._f.write(json.dumps(row, sort_keys=True) + "\n")

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._f.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
