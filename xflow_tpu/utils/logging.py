"""Structured metrics logging — a SURVEY §5 observability gap filled.

The reference logs to stdout only (rank banner, epoch every 30, final
logloss/AUC — lr_worker.cc:202,209, base.h:101-108).  Here every epoch
and eval emits a JSON line with a monotonic timestamp so runs are
machine-comparable; stdout keeps the human-readable reference-style
lines.
"""

from __future__ import annotations

import json
import time
from typing import Any, IO


class MetricsLogger:
    def __init__(self, path: str):
        self._f: IO[str] = open(path, "a", buffering=1)
        self._t0 = time.time()

    def log(self, kind: str, record: dict[str, Any]) -> None:
        row = {"t": round(time.time() - self._t0, 3), "kind": kind}
        row.update(record)
        self._f.write(json.dumps(row, sort_keys=True) + "\n")

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
