"""Evaluation metrics: clamped sigmoid, logloss, rank-sum AUC.

* ``sigmoid_ref`` reproduces the reference clamp exactly (base.h:54-63):
  x < -30 → 1e-6, x > 30 → 1.0, else 1/(1+exp(-x)).
* ``auc_rank_sum`` reproduces the reference algorithm exactly
  (base.h:84-110): sort by pctr descending; walking down, count
  positives seen (tp_n) and add tp_n for every negative — i.e. for each
  negative, the number of positives scored strictly-or-tied above it —
  then divide by P*N.  No tie averaging: its value under ties depends
  on sort order, exactly as the reference's does (std::sort order is
  unspecified within a tie group).  Kept for documentation/tests.
* ``auc_midrank`` is the canonical Mann-Whitney statistic with midrank
  tie handling — the REPORTING metric.  Both the single-host path
  (AucAccumulator) and the multi-host path (HistAuc) use midrank, so
  the same data reports the same AUC on 1 or N hosts (round-2 advisor
  finding: sigmoid_ref's clamps create exact ties at 1e-6/1.0, and the
  two paths previously resolved them differently).
* ``logloss`` deliberately diverges per the SURVEY quirks ledger: the
  reference computes log2-based, un-negated logloss with a stray ``+ +``
  (base.h:97-98); here it is the standard natural-log negative
  log-likelihood, with probabilities clamped to [eps, 1-eps] so the
  sigmoid's exact-1.0 clamp branch doesn't produce inf.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

LOGLOSS_EPS = 1e-6


def sigmoid_ref(x: jax.Array) -> jax.Array:
    p = 1.0 / (1.0 + jnp.exp(-x))
    p = jnp.where(x < -30.0, 1e-6, p)
    p = jnp.where(x > 30.0, 1.0, p)
    return p


def logloss(labels: jax.Array, pctr: jax.Array, weights: jax.Array | None = None):
    """Weighted mean negative log-likelihood (natural log)."""
    if weights is None:
        return logloss_sum(labels, pctr, jnp.ones_like(pctr)) / pctr.size
    denom = jnp.maximum(jnp.sum(weights), 1.0)
    return logloss_sum(labels, pctr, weights) / denom


def logloss_sum(labels: jax.Array, pctr: jax.Array, weights: jax.Array):
    """Weighted SUM of negative log-likelihood — the accumulator form
    used by microbatch scans, where re-normalizing a clamped per-slice
    mean would mis-scale fractional-weight slices."""
    p = jnp.clip(pctr, LOGLOSS_EPS, 1.0 - LOGLOSS_EPS)
    ll = -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
    return jnp.sum(ll * weights)


def auc_rank_sum(labels: np.ndarray, pctr: np.ndarray) -> float:
    """Reference rank-sum AUC (base.h:84-110).  Returns NaN when all
    labels are one class (the reference prints only tp_n then)."""
    labels = np.asarray(labels)
    pctr = np.asarray(pctr)
    order = np.argsort(-pctr, kind="stable")  # pctr descending
    sorted_labels = labels[order]
    pos = sorted_labels > 0.5
    tp_cum = np.cumsum(pos)
    p = int(tp_cum[-1]) if len(tp_cum) else 0
    n = len(labels) - p
    if p == 0 or n == 0:
        return float("nan")
    area = float(tp_cum[~pos].sum())
    return area / (p * n)


def auc_midrank(labels: np.ndarray, pctr: np.ndarray) -> float:
    """Exact rank-sum AUC with midrank tie handling (Mann-Whitney U /
    (P*N)).  Equals ``auc_rank_sum`` whenever pctrs are tie-free;
    under ties every (pos, neg) pair sharing a pctr counts 1/2 —
    sort-order independent, and the value HistAuc converges to.
    Returns NaN when all labels are one class."""
    labels = np.asarray(labels)
    pctr = np.asarray(pctr)
    pos_mask = labels > 0.5
    p = int(pos_mask.sum())
    n = len(labels) - p
    if p == 0 or n == 0:
        return float("nan")
    order = np.argsort(pctr, kind="stable")  # ascending
    sp = pctr[order]
    first = np.empty(len(sp), bool)  # True at each tie group's start
    first[0] = True
    first[1:] = sp[1:] != sp[:-1]
    starts = np.flatnonzero(first)
    ends = np.append(starts[1:], len(sp))
    # midrank of group g = mean of 1-based ranks starts[g]+1 .. ends[g]
    mid = (starts + 1 + ends) / 2.0
    ranks = np.empty(len(sp))
    ranks[order] = mid[np.cumsum(first) - 1]
    u = ranks[pos_mask].sum() - p * (p + 1) / 2.0
    return float(u / (p * n))


class AucAccumulator:
    """Streaming accumulator for (label, pctr) pairs across eval batches
    (the reference accumulates test_auc_vec under a mutex,
    lr_worker.cc:62-68, then computes once).  AUC uses midrank ties —
    see module docstring."""

    def __init__(self) -> None:
        self._labels: list[np.ndarray] = []
        self._pctr: list[np.ndarray] = []

    def add(self, labels: np.ndarray, pctr: np.ndarray, weights: np.ndarray | None = None):
        labels = np.asarray(labels)
        pctr = np.asarray(pctr)
        if weights is not None:
            keep = np.asarray(weights) > 0
            labels, pctr = labels[keep], pctr[keep]
        self._labels.append(labels)
        self._pctr.append(pctr)

    def count(self) -> int:
        return int(sum(len(a) for a in self._labels))

    def compute(self) -> tuple[float, float]:
        """Returns (logloss_ln, auc)."""
        labels = np.concatenate(self._labels) if self._labels else np.zeros(0)
        pctr = np.concatenate(self._pctr) if self._pctr else np.zeros(0)
        if len(labels) == 0:
            return float("nan"), float("nan")
        p = np.clip(pctr, LOGLOSS_EPS, 1.0 - LOGLOSS_EPS)
        ll = -np.mean(labels * np.log(p) + (1.0 - labels) * np.log(1.0 - p))
        return float(ll), auc_midrank(labels, pctr)

    def pairs(self) -> tuple[np.ndarray, np.ndarray]:
        labels = np.concatenate(self._labels) if self._labels else np.zeros(0)
        pctr = np.concatenate(self._pctr) if self._pctr else np.zeros(0)
        return labels, pctr


class HistAuc:
    """Fixed-memory streaming AUC + logloss over quantized pctr buckets.

    Purpose: multi-host evaluation.  Rank-sum AUC is not decomposable
    over shard subsets, and allgathering every host's (label, pctr)
    pairs is O(test set) memory per host (round-1 weak point).  Instead
    each host accumulates two histograms of pctr ∈ [0, 1] (positives /
    negatives per bucket) plus exact logloss partial sums; histograms
    ADD across hosts, so the cross-host reduction is O(buckets).

    AUC uses midrank tie handling: pairs in distinct buckets count
    exactly; pairs sharing a bucket count ½.  With ``buckets = 2^20``
    the absolute error vs the pairwise statistic is bounded by the
    fraction of (pos, neg) pairs whose pctrs share a 1e-6-wide bucket —
    negligible for float32 sigmoid outputs.  (The reference's own tie
    behavior is std::sort-order-dependent and thus unspecified,
    base.h:89-106; midrank is the canonical resolution.  Logloss is
    exact — it sums, no quantization.)
    """

    def __init__(self, buckets: int = 1 << 20):
        self.buckets = buckets
        self.pos = np.zeros(buckets, np.float64)
        self.neg = np.zeros(buckets, np.float64)
        self.ll_sum = 0.0
        self.n = 0.0

    def add(
        self,
        labels: np.ndarray,
        pctr: np.ndarray,
        weights: np.ndarray | None = None,
    ) -> None:
        labels = np.asarray(labels, np.float64)
        pctr = np.asarray(pctr, np.float64)
        if weights is not None:
            keep = np.asarray(weights) > 0
            labels, pctr = labels[keep], pctr[keep]
        if not len(labels):
            return
        idx = np.clip(
            (pctr * self.buckets).astype(np.int64), 0, self.buckets - 1
        )
        is_pos = labels > 0.5
        self.pos += np.bincount(
            idx[is_pos], minlength=self.buckets
        ).astype(np.float64)
        self.neg += np.bincount(
            idx[~is_pos], minlength=self.buckets
        ).astype(np.float64)
        p = np.clip(pctr, LOGLOSS_EPS, 1.0 - LOGLOSS_EPS)
        self.ll_sum += float(
            -(labels * np.log(p) + (1.0 - labels) * np.log(1.0 - p)).sum()
        )
        self.n += float(len(labels))

    def state(self) -> dict[str, np.ndarray]:
        """Additively mergeable cross-host state."""
        return {
            "pos": self.pos,
            "neg": self.neg,
            "scalars": np.asarray([self.ll_sum, self.n], np.float64),
        }

    @classmethod
    def from_state(cls, state: dict[str, np.ndarray]) -> "HistAuc":
        out = cls(buckets=int(np.asarray(state["pos"]).shape[-1]))
        out.pos = np.asarray(state["pos"], np.float64)
        out.neg = np.asarray(state["neg"], np.float64)
        out.ll_sum = float(np.asarray(state["scalars"])[0])
        out.n = float(np.asarray(state["scalars"])[1])
        return out

    def count(self) -> int:
        return int(self.n)

    def num_pos(self) -> int:
        return int(self.pos.sum())

    def compute(self) -> tuple[float, float]:
        """Returns (logloss_ln, auc)."""
        if self.n == 0:
            return float("nan"), float("nan")
        ll = self.ll_sum / self.n
        p_total = self.pos.sum()
        n_total = self.neg.sum()
        if p_total == 0 or n_total == 0:
            return float(ll), float("nan")
        # descending pctr: positives in strictly higher buckets count 1,
        # same-bucket pairs count 1/2 (midrank)
        above = np.cumsum(self.pos[::-1])[::-1] - self.pos
        area = float((self.neg * (above + 0.5 * self.pos)).sum())
        return float(ll), area / float(p_total * n_total)
