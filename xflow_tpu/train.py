"""CLI entry point.

Covers the reference's launch surface (SURVEY §7 stage 6): the binary's
positional ``train_prefix test_prefix model_index epochs`` (main.cc:27-
45) and the run_ps_local.sh / run_ps_dist.sh topologies become one
command:

    python -m xflow_tpu.train --model lr --train PREFIX --test PREFIX \
        --epochs 10 [--optimizer ftrl] [--table-size-log2 22] ...

There is no scheduler and no role dispatch: single host just runs; a
multi-host pod runs the same command per host (JAX distributed
initialization, one process per host), each host reading its own shard
subset — the moral equivalent of DMLC_ROLE/DMLC_PS_ROOT_URI env
bootstrap (scripts/local.sh:8-19) is ``--coordinator`` below.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer

_MODEL_BY_INDEX = {"0": "lr", "1": "fm", "2": "mvm"}  # main.cc:27-45


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="xflow_tpu.train", description="TPU-native sparse CTR trainer"
    )
    p.add_argument("--config", help="JSON config file (flags override it)")
    p.add_argument("--train", dest="train_path", help="train shard prefix")
    p.add_argument("--test", dest="test_path", help="test shard prefix")
    from xflow_tpu.models import model_names

    p.add_argument(
        "--model",
        choices=[*model_names(), "0", "1", "2"],
        help="model family (registry: models/__init__.py; numeric "
        "aliases match the reference argv[3])",
    )
    p.add_argument("--epochs", type=int)
    p.add_argument("--optimizer", choices=["ftrl", "sgd"])
    p.add_argument("--batch-size", type=int, dest="batch_size")
    p.add_argument("--table-size-log2", type=int, dest="table_size_log2")
    p.add_argument("--v-dim", type=int, dest="v_dim")
    p.add_argument("--ffm-v-dim", type=int, dest="ffm_v_dim")
    p.add_argument("--emb-dim", type=int, dest="emb_dim")
    p.add_argument("--hidden-dim", type=int, dest="hidden_dim")
    p.add_argument(
        "--tower-split-field", type=int, dest="tower_split_field",
        help="two_tower: fields < split are user-side, >= item-side",
    )
    p.add_argument(
        "--tower-dim", type=int, dest="tower_dim",
        help="two_tower: tower output width (= item-index row width)",
    )
    p.add_argument(
        "--cross-layers", type=int, dest="cross_layers",
        help="dcn: explicit cross-network depth",
    )
    p.add_argument("--max-nnz", type=int, dest="max_nnz")
    p.add_argument("--max-fields", type=int, dest="max_fields")
    p.add_argument("--block-mib", type=int, dest="block_mib")
    p.add_argument(
        "--microbatch", type=int, dest="microbatch",
        help="gradient-accumulation slices per step (1 = off): same "
        "optimizer step at 1/N the batch-shaped memory",
    )
    p.add_argument(
        "--update-mode", dest="update_mode",
        choices=["dense", "sparse", "sequential"],
        help="dense: scatter-add + full-table optimizer pass (TPU-fast); "
        "sparse: sort/consolidate + touched-rows-only update (small "
        "batches, CPU); sequential: optimizer applies per --microbatch "
        "slice inside the dispatched step, so the effective optimizer "
        "batch is batch-size/microbatch (small-batch convergence at "
        "device dispatch rates)",
    )
    p.add_argument("--alpha", type=float)
    p.add_argument("--beta", type=float)
    p.add_argument("--lambda1", type=float)
    p.add_argument("--lambda2", type=float)
    p.add_argument("--sgd-lr", type=float, dest="sgd_lr")
    p.add_argument("--seed", type=int)
    p.add_argument("--num-devices", type=int, dest="num_devices")
    p.add_argument("--no-hash", action="store_true", help="numeric fids, keep values")
    p.add_argument(
        "--hot-size-log2", type=int, dest="hot_size_log2",
        help="log2 rows of the frequency-hot MXU table (0 = off)",
    )
    p.add_argument("--hot-nnz", type=int, dest="hot_nnz")
    p.add_argument(
        "--freq-sample-mib", type=int, dest="freq_sample_mib",
        help="MiB of training data sampled to build the hot-key remap",
    )
    p.add_argument(
        "--hot-dtype", choices=["float32", "bfloat16"], dest="hot_dtype"
    )
    p.add_argument(
        "--sequential-inner", dest="sequential_inner",
        choices=["dense", "sparse", "hot"],
        help="per-slice update strategy under --update-mode sequential: "
        "dense = full-table pass (T<=2^24); sparse = touched-rows only "
        "(required at 2^28-scale tables); hot = hot-fine/cold-coarse "
        "(per-slice updates only the hot head, cold tail batched per "
        "dispatch window — needs --hot-size-log2)",
    )
    p.add_argument(
        "--hot-windowend", dest="hot_windowend",
        choices=["auto", "dense", "sparse"],
        help="window-end cold-tail form for --sequential-inner hot: "
        "dense = [T, D] buffer + full-table pass (small tables); "
        "sparse = consolidated touched-rows update, table-size "
        "independent (the 2^28 form; analysis rule XF010/XF014); "
        "auto = sparse from --table-size-log2 24 up",
    )
    p.add_argument(
        "--cold-consolidate", action="store_true", default=None,
        dest="cold_consolidate",
        help="merge duplicate cold keys (shared argsort + segment-sum) "
        "before the dense-mode scatter-add — pays off for D>1 models "
        "on zipf batches (docs/PERF.md)",
    )
    p.add_argument(
        "--store-mode", choices=["dense", "tiered"], dest="store_mode",
        help="parameter residency (docs/STORE.md): dense = the whole "
        "[T, D] table in device HBM; tiered = bounded HBM hot tier + "
        "host cold store with async promotion — the 2^28-scale form "
        "(FM/MVM/FFM at --table-size-log2 28 only fit this way)",
    )
    p.add_argument(
        "--hot-capacity-log2", type=int, dest="hot_capacity_log2",
        help="log2 rows of the HBM hot tier under --store-mode tiered "
        "(must not exceed --table-size-log2)",
    )
    p.add_argument(
        "--store-promote-every", type=int, dest="store_promote_every",
        help="apply promotion/demotion plans every N train steps",
    )
    p.add_argument(
        "--input-streams", type=int, dest="input_streams",
        help="parallel sharded input fan-out (io/fanout.py): N "
        "concurrent shard-reader streams, each with its own read -> "
        "parse -> compact worker; batch order stays the serial shard "
        "order, so training is bitwise-identical to 1 (the default, "
        "serial reader) — docs/PERF.md \"Input fan-out\"",
    )
    p.add_argument(
        "--transfer-ahead-depth", type=int, dest="transfer_ahead_depth",
        help="device staging ring depth: batches staged ahead on "
        "worker threads (put_batch overlap; >= 2 = double buffering, "
        "deeper absorbs link jitter)",
    )
    p.add_argument(
        "--wire-mode", choices=["auto", "full", "compact"], dest="wire_mode",
        help="host->device batch format; compact ships ~16x fewer "
        "bytes/entry (hash mode; slot-reading models add a u8 slots "
        "plane, ~3x)",
    )
    p.add_argument("--pred-out", dest="pred_out")
    p.add_argument(
        "--pred-style", choices=["single", "per_block"], dest="pred_style",
        help="'per_block': pred_out is a directory; every host writes "
        "pred_<rank>_<block>.txt per eval batch (reference artifact "
        "granularity, lr_worker.cc:74-78)",
    )
    p.add_argument(
        "--metrics-out", dest="metrics_out",
        help="structured metrics JSONL (schema: obs/schema.py); "
        "summarize with `python -m xflow_tpu.obs summarize FILE`",
    )
    p.add_argument(
        "--obs-trace-out", dest="obs_trace_out",
        help="host-side span trace (Chrome trace-event JSON for "
        "Perfetto) written here on exit",
    )
    p.add_argument(
        "--obs-trace-capacity", type=int, dest="obs_trace_capacity",
        help="span ring-buffer size (newest N spans kept)",
    )
    p.add_argument(
        "--obs-flight-out", dest="obs_flight_out",
        help="flight-recorder dump path: crash/hang forensics (recent "
        "phases, batch shapes, thread stacks) written here atomically "
        "on unhandled exception, preemption, or watchdog escalation; "
        "read with `python -m xflow_tpu.obs doctor RUN --flight FILE`",
    )
    p.add_argument(
        "--obs-watchdog", action="store_true", default=None,
        dest="obs_watchdog",
        help="enable the stall watchdog: classifies hot-loop silence "
        "into input starvation / device hang, emits `health` JSONL "
        "rows, escalates to a flight dump (docs/OBSERVABILITY.md "
        "\"Diagnosing a sick run\")",
    )
    p.add_argument(
        "--obs-watchdog-input-s", type=float, dest="obs_watchdog_input_s",
        help="input-starvation silence threshold, seconds",
    )
    p.add_argument(
        "--obs-watchdog-device-s", type=float, dest="obs_watchdog_device_s",
        help="device-hang silence threshold, seconds",
    )
    p.add_argument(
        "--obs-lock-sanitizer", action="store_true", default=None,
        dest="obs_lock_sanitizer",
        help="arm the lock-order sanitizer (analysis/sanitizer.py): "
        "instrument the obs-stack locks so actual acquisition orders "
        "are recorded and cross-checkable against the static XF007 "
        "graph (docs/ANALYSIS.md); debug/stress tooling, zero "
        "overhead when off",
    )
    p.add_argument("--profile-dir", dest="profile_dir")
    p.add_argument("--profile-steps", type=int, dest="profile_steps")
    p.add_argument("--profile-start-step", type=int, dest="profile_start_step")
    p.add_argument("--checkpoint-dir", dest="checkpoint_dir")
    p.add_argument(
        "--checkpoint-every-steps", type=int, dest="checkpoint_every_steps"
    )
    p.add_argument(
        "--checkpoint-keep", type=int, dest="checkpoint_keep",
        help="keep only the newest K checkpoints (0 = keep all)",
    )
    p.add_argument(
        "--eval-every", type=int, dest="eval_every_epochs",
        help="run evaluation every N epochs during training (0 = only "
        "at the end) — the reference evaluates once, after all epochs "
        "(lr_worker.cc:212-215)",
    )
    p.add_argument(
        "--resume", nargs="?", const="latest", default=None,
        choices=["latest", "auto"],
        help="resume from a checkpoint: bare --resume (or 'latest') "
        "follows the LATEST marker; 'auto' restores the newest "
        "COMPLETE generation, skipping half-written or corrupted ones "
        "with a health row (docs/ROBUSTNESS.md) — the flag to reach "
        "for after a kill/preemption mid-checkpoint",
    )
    p.add_argument(
        "--chaos-spec", dest="chaos_spec",
        help="arm the seeded failpoint fabric, e.g. "
        "'seed=7;loader.read_block:nth=2' (docs/ROBUSTNESS.md; the "
        "XFLOW_CHAOS env var arms the same machinery)",
    )
    p.add_argument(
        "--io-retries", type=int, dest="io_retries",
        help="transient shard-read/parse and cold-store retry budget "
        "per block (exponential backoff; exhausted retries quarantine "
        "the block)",
    )
    p.add_argument(
        "--max-quarantined-frac", type=float, dest="max_quarantined_frac",
        help="abort the stream once quarantined blocks exceed "
        "max(1, ceil(frac * blocks seen)) — skip-and-continue is for "
        "isolated corruption, not a rotten stream",
    )
    p.add_argument(
        "--export-artifact", dest="export_artifact",
        help="after training, freeze the model into a serving artifact "
        "at this directory (serve/artifact.py; score it with "
        "`python -m xflow_tpu.serve` — docs/SERVING.md)",
    )
    p.add_argument(
        "--platform",
        choices=["tpu", "cpu", "gpu"],
        help="force the JAX backend (overrides plugin auto-selection; "
        "needed e.g. to run the distributed path on CPU processes)",
    )
    p.add_argument(
        "--coordinator",
        help="host:port of process 0 for multi-host (jax.distributed); "
        "also requires --process-id and --num-processes",
    )
    p.add_argument("--process-id", type=int)
    p.add_argument("--num-processes", type=int)
    p.add_argument("--skip-eval", action="store_true")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    base = {}
    if args.config:
        with open(args.config) as f:
            base = json.load(f)
    field_names = {f.name for f in dataclasses.fields(Config)}
    for name in field_names:
        val = getattr(args, name, None)
        if val is not None:
            base[name] = val
    if args.model is not None:
        base["model"] = _MODEL_BY_INDEX.get(args.model, args.model)
    if args.no_hash:
        base["hash_mode"] = False
    return Config(**base)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.platform:
        # must precede any backend initialization (the env var alone can
        # be overridden by platform plugins registered at site import)
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.coordinator:
        import jax

        jax.distributed.initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    cfg = config_from_args(args)
    if not cfg.train_path:
        print("error: --train is required", file=sys.stderr)
        return 2
    # context manager: metrics JSONL + trace are flushed/closed on every
    # exit path, including exceptions (the logger itself also closes on
    # train()'s own preemption/crash paths)
    with Trainer(cfg) as trainer:
        if args.resume:
            cursor = trainer.restore(auto=(args.resume == "auto"))
            if cursor:
                print(f"resumed at {cursor}", file=sys.stderr)
        history = trainer.train()
        if history and history[-1].get("preempted"):
            print(
                "preempted: checkpoint saved, resume with --resume",
                file=sys.stderr,
            )
            return 0
        if cfg.test_path and not args.skip_eval:
            trainer.evaluate()
        if args.export_artifact:
            from xflow_tpu.serve.artifact import export_artifact

            path = export_artifact(trainer, args.export_artifact)
            print(f"exported serving artifact to {path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
