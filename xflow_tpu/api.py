"""Embed-as-a-library API.

The reference ships a (disabled) C ABI wrapper signalling an intended
embeddable API: ``XFCreate(handle, train, test)`` / ``XFStartTrain``
(c_api.h:26-41, build commented out at CMakeLists.txt:28).  This class
is that capability, done properly: construct with paths + config
overrides, then train / evaluate / predict / save / restore.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


class XFlow:
    def __init__(self, train_path: str = "", test_path: str = "", **overrides: Any):
        self.config = Config(train_path=train_path, test_path=test_path, **overrides)
        self.trainer = Trainer(self.config)

    def train(self) -> list[dict]:
        return self.trainer.train()

    def evaluate(self, pred_out: str | None = None) -> dict:
        return self.trainer.evaluate(pred_out=pred_out)

    def predict_batch(self, batch) -> np.ndarray:
        """pctr for one padded Batch built in the raw hash key space
        (see io/batch.py).  When the model was trained with a hot table,
        the trainer's frequency remap is applied here — the remap is
        part of the model (io/freq.py)."""
        import jax

        arrays = self.trainer.step.put_batch(self.trainer.prepare_batch(batch))
        return np.asarray(
            jax.device_get(self.trainer.step.predict(self.trainer.state, arrays))
        )

    def save(self) -> str | None:
        return self.trainer.save()

    def restore(self) -> dict | None:
        return self.trainer.restore()

    def close(self) -> None:
        """Flush/close observability outputs (metrics JSONL, trace)."""
        self.trainer.close()

    def __enter__(self) -> "XFlow":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
