"""Embed-as-a-library API.

The reference ships a (disabled) C ABI wrapper signalling an intended
embeddable API: ``XFCreate(handle, train, test)`` / ``XFStartTrain``
(c_api.h:26-41, build commented out at CMakeLists.txt:28).  This class
is that capability, done properly: construct with paths + config
overrides, then train / evaluate / predict / save / restore.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from xflow_tpu.config import Config
from xflow_tpu.trainer import Trainer


class XFlow:
    def __init__(self, train_path: str = "", test_path: str = "", **overrides: Any):
        self.config = Config(train_path=train_path, test_path=test_path, **overrides)
        self.trainer = Trainer(self.config)
        self._engine = None

    def train(self) -> list[dict]:
        return self.trainer.train()

    def evaluate(self, pred_out: str | None = None) -> dict:
        return self.trainer.evaluate(pred_out=pred_out)

    def predict_batch(self, batch) -> np.ndarray:
        """pctr for one Batch built in the raw hash key space (see
        io/batch.py) — the hot-table remap is applied inside.  Routed
        through a PredictEngine over the LIVE trainer state (weights
        always current), so batch sizes snap onto the engine's shape
        buckets: scoring a previously unseen batch size pads instead of
        triggering a fresh XLA compile (serve/engine.py)."""
        if self.config.store_mode == "tiered":
            raise ValueError(
                "predict_batch over the LIVE trainer state needs the "
                "whole table in device memory, which store_mode="
                "'tiered' deliberately avoids — export_artifact() and "
                "score through PredictEngine.load (the export folds "
                "both tiers into one logical table; docs/STORE.md)"
            )
        if self._engine is None:
            from xflow_tpu.serve.engine import PredictEngine

            self._engine = PredictEngine(
                self.config,
                self.trainer.state,
                remap=self.trainer.remap,
                mesh=self.trainer.mesh,
            )
        self._engine.update_state(self.trainer.state)
        return self._engine.predict(batch)

    def export_artifact(self, directory: str) -> str:
        """Freeze the current weights into a serving artifact
        (serve/artifact.py) loadable by PredictEngine with no Trainer,
        loader, or optimizer state."""
        from xflow_tpu.serve.artifact import export_artifact

        return export_artifact(self.trainer, directory)

    def save(self) -> str | None:
        return self.trainer.save()

    def restore(self) -> dict | None:
        return self.trainer.restore()

    def close(self) -> None:
        """Flush/close observability outputs (metrics JSONL, trace)."""
        self.trainer.close()

    def __enter__(self) -> "XFlow":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
