"""The pjit'd train/predict step — workers, server, and optimizer fused
into one XLA program.

One reference minibatch costs: per-thread sort+unique
(lr_worker.cc:147-166), a blocking Pull RPC, the loss/gradient joins
(lr_worker.cc:100-143), a blocking Push RPC, and the server-side FTRL
loop (ftrl.h:54-79).  Here the whole round trip is a single jitted
function over sharded arrays:

    gather rows → logit → clamped sigmoid → residual
    → per-occurrence grads → consolidate per unique key
    → gather state rows → optimizer recurrence → scatter back

Gradient scaling matches the reference: the per-key gradient is the sum
of (sigma(logit)-y) contributions over the minibatch divided by the
real example count (lr_worker.cc:116-118).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from xflow_tpu.config import Config
from xflow_tpu.io.batch import Batch
from xflow_tpu.models.base import BatchArrays, Model
from xflow_tpu.obs import NULL_OBS
from xflow_tpu.ops.sparse import (
    consolidate_apply,
    consolidate_indexed,
    consolidate_plan,
    gather_rows,
    scatter_rows,
)
from xflow_tpu.optim.base import Optimizer
from xflow_tpu.parallel.mesh import batch_sharding, table_sharding
from xflow_tpu.utils.metrics import logloss, logloss_sum, sigmoid_ref

# State pytree:
# {"tables": {name: {"param": [T,D], <aux>: [T,D]...}},
#  "dense": {name: array} (replicated; {} for table-only models),
#  "step": int32 scalar}
State = dict[str, Any]


def grads_from_rows(model, rows: dict, dense: dict, mbatch: BatchArrays,
                    num_real: jax.Array):
    """pctr + per-occurrence gradients, rows already gathered: the ONE
    forward/backward shared by TrainStep (all update modes) and the
    tiered store's hot+miss step (store/hot.py) so the two cannot
    drift.  ``mbatch`` is the model view (hot/cold sections already
    concatenated where applicable).  Returns (pctr, occ_grads,
    grad_dense_or_None); occ_grads are residual-scaled and divided by
    ``num_real``, the reference's mean-gradient semantics
    (lr_worker.cc:116-118)."""
    if getattr(model, "autodiff", False):
        # Autodiff path (FFM, wide&deep — no reference gradient
        # quirks): stable BCE-with-logits; d/dlogit = sigmoid - y,
        # the same residual semantics as the explicit path.
        def loss_fn(rows_, dense_):
            logit_ = model.logit(rows_, mbatch, dense_)
            nll = jax.nn.softplus(logit_) - mbatch["labels"] * logit_
            return (
                jnp.sum(nll * mbatch["weights"]) / num_real,
                logit_,
            )

        (_, logit), (grad_rows, grad_dense) = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(rows, dense)
        return sigmoid_ref(logit), grad_rows, (grad_dense or None)
    logit = model.logit(rows, mbatch)
    pctr = sigmoid_ref(logit)
    # Residual "loss" exactly as the reference names it
    # (lr_worker.cc:121-143): sigma(wx) - y, zeroed for pad
    # examples, pre-divided by batch size for the mean-gradient
    # semantics.
    residual = (pctr - mbatch["labels"]) * mbatch["weights"] / num_real
    grad_logit = model.grad_logit(rows, mbatch)
    occ_grads = {
        name: g * residual[:, None, None]
        for name, g in grad_logit.items()
    }
    return pctr, occ_grads, None


def apply_dense_sgd(dense: dict, grad_dense, lr: float) -> dict:
    """Dense (MLP) params take plain SGD regardless of the table
    optimizer (models/wide_deep.py rationale) — the ONE copy of that
    rule, shared by TrainStep (per-dispatch and per-slice application)
    and the tiered store's step (store/hot.py)."""
    if not dense or grad_dense is None:
        return dense
    return jax.tree.map(lambda p, g: p - lr * g, dense, grad_dense)


def init_state(model: Model, optimizer: Optimizer, cfg: Config, mesh) -> State:
    """Create sharded zero/random-initialized tables (plus replicated
    dense params for models that have them).

    v-table random init reproduces the reference's lazy server-side
    N(0,1)*1e-2 (ftrl.h:113-120) eagerly; see optim/ftrl.py.
    """
    from xflow_tpu.parallel.mesh import replicated

    sharding = table_sharding(mesh)
    rng = jax.random.PRNGKey(cfg.seed)
    tables: dict[str, dict[str, jax.Array]] = {}
    for i, spec in enumerate(model.tables()):
        shape = (cfg.table_size, spec.dim)
        # once-per-Trainer-construction table init, one jit per table
        # spec by design — not a hot-loop retrace (xf: ignore[XF001])
        init_fn = jax.jit(
            functools.partial(spec.init, shape=shape), out_shardings=sharding
        )
        param = init_fn(jax.random.fold_in(rng, i))
        entry = {"param": param}
        for aux_name, aux in optimizer.init_aux(param).items():
            entry[aux_name] = jax.device_put(aux, sharding)
        tables[spec.name] = entry
    dense = {}
    if hasattr(model, "dense_init"):
        dense = jax.tree.map(
            lambda a: jax.device_put(a, replicated(mesh)),
            model.dense_init(jax.random.fold_in(rng, 1000)),
        )
    return {"tables": tables, "dense": dense, "step": jnp.zeros((), jnp.int32)}


def batch_to_arrays(batch: Batch) -> BatchArrays:
    out = {
        "keys": jnp.asarray(batch.keys),
        "slots": jnp.asarray(batch.slots),
        "vals": jnp.asarray(batch.vals),
        "mask": jnp.asarray(batch.mask),
        "labels": jnp.asarray(batch.labels),
        "weights": jnp.asarray(batch.weights),
    }
    if batch.hot_nnz:
        out["hot_keys"] = jnp.asarray(batch.hot_keys)
        out["hot_slots"] = jnp.asarray(batch.hot_slots)
        out["hot_vals"] = jnp.asarray(batch.hot_vals)
        out["hot_mask"] = jnp.asarray(batch.hot_mask)
    return out


def validate_compact_batch(batch: Batch) -> None:
    """Compact-wire invariants: binary features (val 1 wherever mask 1)
    and 0/1 labels/weights.  Loader-produced hash-mode batches satisfy
    them by construction, so put_batch validates only the FIRST batch
    per TrainStep — full [B,K] scans on every batch would burn the host
    CPU the compact format exists to relieve."""
    import numpy as np

    if not (
        np.array_equal(batch.vals * batch.mask, batch.mask)
        and np.array_equal(batch.hot_vals * batch.hot_mask, batch.hot_mask)
    ):
        raise ValueError(
            "compact wire requires binary features (val 1 wherever "
            "mask 1); set wire_mode='full' for value-carrying batches"
        )
    for arr in (batch.labels, batch.weights):
        if not np.isin(arr, (0.0, 1.0)).all():
            raise ValueError(
                "compact wire requires 0/1 labels and weights; set "
                "wire_mode='full'"
            )


def compact_wire_np(
    batch: Batch, ship_slots: bool = False, hot_u16: bool = False
) -> dict:
    """The numpy (host) half of the compact wire: sentinel-coded int32
    keys + uint8 labels/weights, plus a uint8 slots plane for models
    that read field ids.  Shared by batch_to_compact and the bench's
    host-feed measurement so the measured per-batch work is by
    construction exactly the work the training feed performs.

    hot_u16: ship the hot section's keys as uint16 (sentinel 0xFFFF)
    instead of int32 — hot row ids are < H by construction
    (io/batch.py::split_hot), so with H <= 2^15 the plane halves with
    no id/sentinel collision possible.  At the lr flagship geometry
    (cold 16 + hot 32) this takes the wire from 194 to 130
    bytes/example — a direct multiplier on the link-bound e2e path.

    The u8 slot clamp (min(slot, 255)) is lossless under the models'
    shared out-of-range semantics: every slot consumer drops fields >=
    max_fields via a one-hot row of zeros (mvm.py:76, ffm.py:11,
    wide_deep.py:73), so with max_fields <= 255 (enforced at TrainStep
    init) a clamped slot lands in the ignored range either way."""
    import numpy as np

    from xflow_tpu.io.batch import narrow_keys_i32

    def sentinel(keys, mask):
        # narrow THROUGH the audited choke point (XF011): loader-built
        # batches are int32 already (free pass-through); an external
        # 64-bit-key batch is range-checked, never wrapped.  Masked
        # lanes are zeroed in the WIDE dtype first — padding may carry
        # unreduced garbage, and only live keys owe the range contract
        # — then the -1 sentinel is applied in int32 space.
        live = narrow_keys_i32(np.where(mask > 0, keys, 0))
        return np.where(mask > 0, live, np.int32(-1))

    def slots_u8(slots):
        # anything outside [0, 255] maps to 255 (>= max_fields → the
        # models ignore it, like the full wire does for negative or
        # oversized slots) — a plain uint8 cast would WRAP negatives
        # into the live field range
        return np.where(
            (slots < 0) | (slots > 255), 255, slots
        ).astype(np.uint8)

    out = {
        "ckeys": sentinel(batch.keys, batch.mask),
        "labels_u8": batch.labels.astype(np.uint8),
        "weights_u8": batch.weights.astype(np.uint8),
    }
    if ship_slots:
        out["slots_u8"] = slots_u8(batch.slots)
    if batch.hot_nnz:
        if hot_u16:
            out["hot_ckeys_u16"] = np.where(
                batch.hot_mask > 0, batch.hot_keys, 0xFFFF
            ).astype(np.uint16)
        else:
            out["hot_ckeys"] = sentinel(batch.hot_keys, batch.hot_mask)
        if ship_slots:
            out["hot_slots_u8"] = slots_u8(batch.hot_slots)
    return out


def _checked(batch: Batch, check: bool) -> Batch:
    if check:
        validate_compact_batch(batch)
    return batch


def batch_to_compact(
    batch: Batch,
    check: bool = True,
    ship_slots: bool = False,
    hot_u16: bool = False,
) -> BatchArrays:
    """Compact wire (Config.wire_mode): sentinel-coded keys + uint8
    labels/weights — ~16x fewer bytes/entry than the full format for
    slot-free models (lr, fm); slot-reading models (mvm, ffm,
    wide_deep) add a uint8 slots plane (~3x).  Only valid when vals
    are identically 1 for real entries (hash mode); _expand_wire
    reconstructs vals/mask (and zero slots when none shipped) on
    device."""
    if check:
        validate_compact_batch(batch)
    return {
        k: jnp.asarray(v)
        for k, v in compact_wire_np(batch, ship_slots, hot_u16).items()
    }


def _interleaved_slices(batch: BatchArrays, s: int) -> BatchArrays:
    """Split the batch dim into s scan slices with INTERLEAVED example
    assignment (example i → slice i % s): each slice stays evenly
    spread over the batch-sharded mesh axis, so GSPMD sees a local
    strided view per slice instead of the reshard/all-to-all a
    contiguous split would force (slice 0 = first B/s rows = one
    device's shard).  Both scan modes are composition-insensitive:
    accumulate is order-independent, and sequential's slice sequence
    is an arbitrary partition of the dispatch window by design."""
    return {
        k: v.reshape((v.shape[0] // s, s) + v.shape[1:]).swapaxes(0, 1)
        for k, v in batch.items()
    }


class TrainStep:
    """Holds the compiled train/predict functions for one (model,
    optimizer, config, mesh) combination."""

    def __init__(self, model: Model, optimizer: Optimizer, cfg: Config, mesh):
        self.model = model
        self.optimizer = optimizer
        self.cfg = cfg
        self.mesh = mesh
        self._bsharding = batch_sharding(mesh)
        self._hot_dtype = (
            jnp.bfloat16 if cfg.hot_dtype == "bfloat16" else jnp.float32
        )
        # Compact wire eligibility (Config.wire_mode): requires binary
        # vals (hash mode).  Slot-reading models additionally need
        # max_fields <= 255 so the u8 slots plane's clamp stays inside
        # the models' ignored range (compact_wire_np docstring).
        self._ship_slots = bool(getattr(model, "uses_slots", True))
        # hot ids fit u16 with the 0xFFFF sentinel only below 2^15
        # rows (compact_wire_np docstring)
        self._hot_u16 = bool(
            cfg.hot_size_log2 and cfg.hot_size_log2 <= 15
        )
        # Per-table MXU hot opt-out (TableSpec.hot): opted-out tables
        # keep their hot-plane occurrences on plain DMA gather/scatter.
        self._mxu_hot = {spec.name: spec.hot for spec in model.tables()}
        # The hot-inner/opt-out conflict only exists when the hot inner
        # actually RUNS — update_mode must be 'sequential'.  In dense or
        # sparse mode sequential_inner is an unused knob (ffm + dense +
        # inner='hot' is a legal Config), so rejecting it at build was a
        # false failure (ADVICE round-5 low #2).
        if cfg.update_mode == "sequential" and (
            cfg.sequential_inner == "hot"
        ) and not all(
            self._mxu_hot.values()
        ):
            opted_out = [n for n, v in self._mxu_hot.items() if not v]
            raise ValueError(
                "sequential_inner='hot' carries every table's head in "
                f"the scan; model {model.name!r} opts table(s) "
                f"{opted_out} out of the MXU hot path (TableSpec.hot)"
            )
        compact_ok = cfg.hash_mode and not (
            self._ship_slots and cfg.max_fields > 255
        )
        if cfg.wire_mode == "compact" and not compact_ok:
            raise ValueError(
                "wire_mode='compact' requires hash_mode (binary vals) "
                "and, for slot-reading models, max_fields <= 255; model "
                f"{model.name!r} / hash_mode={cfg.hash_mode} / "
                f"max_fields={cfg.max_fields} does not qualify"
            )
        self.compact_wire = cfg.wire_mode != "full" and compact_ok
        self._compact_validated = False
        # Hot-path implementation (ops/hot.py): one-hot MXU matmuls on
        # TPU, gather + segment-sum elsewhere (Config.hot_impl) — the
        # MXU trick measured 3.3x SLOWER than the gather on the CPU
        # backend (docs/PERF.md "Wire format and compaction").
        platform = str(self.mesh.devices.ravel()[0].platform)
        self._hot_impl = (
            cfg.hot_impl
            if cfg.hot_impl != "auto"
            else ("mxu" if platform == "tpu" else "seg")
        )
        # Window-end form for the hot sequential inner
        # (Config.hot_windowend): the dense [T, D] cold-tail pass is
        # fine while tables are small; from table_size_log2 >= 24 the
        # transient would dwarf the update itself, so auto routes
        # through the consolidated touched-rows update.
        self._windowend = (
            cfg.hot_windowend
            if cfg.hot_windowend != "auto"
            else ("sparse" if cfg.table_size_log2 >= 24 else "dense")
        )
        # Dictionary-wire eligibility (Config.wire_dedup; io/compact.py):
        # host-side batch compaction needs the compact-wire invariants
        # PLUS single process + single-device mesh (the dictionary and
        # flat occurrence streams have no batch-axis sharding), u8
        # per-row counts, and hot ids that fit the tiered encoding.
        kh = cfg.hot_nnz if cfg.hot_size else 0
        dict_ok = (
            compact_ok
            and jax.process_count() == 1
            and self.mesh.devices.size == 1
            and cfg.max_nnz <= 255
            and kh <= 255
            and (not cfg.hot_size_log2 or cfg.hot_size_log2 <= 16)
        )
        if cfg.wire_dedup == "on" and not dict_ok:
            raise ValueError(
                "wire_dedup='on' requires the compact-wire invariants "
                "(hash_mode; max_fields <= 255 for slot models), a "
                "single-process single-device mesh, max_nnz/hot_nnz "
                "<= 255, and hot_size_log2 <= 16"
            )
        self.dict_wire = (
            cfg.wire_mode != "full"
            and cfg.wire_dedup != "off"
            and dict_ok
        )
        # Hierarchical parameter store (Config.store_mode; store/):
        # under 'tiered' the table state is the store's hot tier + host
        # cold rows, the wire is the store's refs/miss format (the
        # compact/dict wires encode raw table keys, which the tiered
        # step never sees), and train/predict dispatch through the
        # store's hot+miss jits (store/hot.py).
        self.store = None
        if cfg.store_mode == "tiered":
            if jax.process_count() > 1:
                raise ValueError(
                    "store_mode='tiered' is single-process for now: the "
                    "cold row store is host-local (multi-host would "
                    "need a sharded cold tier — docs/STORE.md)"
                )
            from xflow_tpu.store.tiered import TieredStore

            self.store = TieredStore(model, optimizer, cfg, mesh)
            self.compact_wire = False
            self.dict_wire = False
        # Observability hook (obs/__init__.py): the trainer swaps in a
        # live Obs; the default NULL_OBS makes every span a shared no-op
        # object, so direct users (bench.py run()) pay nothing.
        self.obs = NULL_OBS
        self.train = jax.jit(self._train_impl, donate_argnums=0)
        self.predict = jax.jit(self._predict_impl)
        if self.store is not None:
            # tiered predict consumes the refs/miss wire, not key
            # planes — rebind AFTER the plain jit binding above so the
            # analysis pass still discovers _predict_impl as an entry
            self.predict = self.store.hot.predict

    # -- helpers -----------------------------------------------------------

    def put_batch(self, batch, predict: bool = False) -> BatchArrays:
        """Host->device transfer, booked as the 'h2d' phase; accepts a
        Batch or a pre-compacted CompactBatch (packed-cache v2
        records).  Under trainer._transfer_ahead this runs on a worker
        thread and the seconds land in the epoch record's overlapped
        dict; called inline (multi-host, eval) they are
        main-thread-exclusive.  ``predict`` (eval/serving callers)
        matters only to the tiered store: predict misses ship the
        param plane alone — the optimizer slots never score, and the
        staging ring is off there, so the saved fetch+transfer is
        serial time (store/tiered.py).  The dense wire ignores it."""
        with self.obs.phase("h2d"):
            return self._put_batch_impl(batch, predict=predict)

    @property
    def wire_format(self) -> str:
        return (
            "tiered" if self.store is not None
            else "dict" if self.dict_wire
            else "compact" if self.compact_wire
            else "full"
        )

    def _book_wire(self, nbytes: int, examples: int, cb=None) -> None:
        """Wire accounting counters behind the trainer's per-epoch
        ``wire`` metrics row (obs/schema.py): bytes that crossed the
        link, examples they carried, and — dict wire — the cold
        occurrence/unique-touch compaction the host performed."""
        self.obs.counter("wire.bytes", nbytes)
        self.obs.counter("wire.examples", examples)
        self.obs.counter("wire.batches")
        if cb is not None:
            self.obs.counter("wire.cold_occ", cb.n_cold)
            self.obs.counter("wire.cold_touched", cb.cold_touched)

    def _dict_geometry_ok(self, batch) -> bool:
        """A batch rides the dict wire only at the loader geometry the
        decode is traced for; other widths (external predict batches)
        keep the plain wire."""
        cfg = self.cfg
        kh = cfg.hot_nnz if cfg.hot_size else 0
        return batch.max_nnz == cfg.max_nnz and batch.hot_nnz == kh

    def precompact(self, batch):
        """Host dictionary compaction off the consumer thread: the
        CompactBatch ``put_batch`` would otherwise build inline, or the
        batch unchanged when the dict wire (or this batch's geometry)
        doesn't apply.  The input fan-out's stream workers
        (io/fanout.py) run this per batch so compaction parallelizes
        across N streams instead of serializing on the staging ring —
        put_batch on the result is a plane collection plus the h2d
        transfer.  Deterministic: the compacted planes are exactly the
        inline path's, so fan-out training stays bitwise-identical."""
        from xflow_tpu.io.compact import CompactBatch

        if (
            isinstance(batch, CompactBatch)
            or not self.dict_wire
            or self.store is not None
            or not self._dict_geometry_ok(batch)
        ):
            return batch
        cb = CompactBatch.from_batch(
            batch, self.cfg.table_size, self.cfg.hot_size,
            # the put_batch latch: racing streams at worst BOTH validate
            # their first batch — extra checking (xf: ignore[XF008])
            check=not self._compact_validated,
        )
        self._compact_validated = True  # same latch; xf: ignore[XF008]
        return cb

    def host_wire_np(self, batch, check: bool = False):
        """The host half of put_batch: the numpy planes that cross the
        link for ``batch`` under this step's wire format, plus the
        CompactBatch when the dict wire ran (None otherwise).  Shared
        with bench.py's host-feed measurement so the measured per-batch
        work is by construction exactly the training feed's."""
        from xflow_tpu.io.compact import CompactBatch

        if isinstance(batch, CompactBatch):
            # pre-compacted (packed-cache v2 records): plane collection
            # only — zero per-batch host work
            if self.dict_wire and self._dict_geometry_ok(batch):
                return batch.wire(self._ship_slots), batch
            batch = batch.expand()
        if self.dict_wire and self._dict_geometry_ok(batch):
            cb = CompactBatch.from_batch(
                batch, self.cfg.table_size, self.cfg.hot_size,
                check=check,
            )
            return cb.wire(self._ship_slots), cb
        if self.compact_wire:
            return compact_wire_np(
                _checked(batch, check),
                ship_slots=self._ship_slots,
                hot_u16=self._hot_u16,
            ), None
        wire = {
            "keys": batch.keys, "slots": batch.slots,
            "vals": batch.vals, "mask": batch.mask,
            "labels": batch.labels, "weights": batch.weights,
        }
        if batch.hot_nnz:
            wire.update({
                "hot_keys": batch.hot_keys,
                "hot_slots": batch.hot_slots,
                "hot_vals": batch.hot_vals,
                "hot_mask": batch.hot_mask,
            })
        return wire, None

    def _put_batch_tiered(self, batch, predict: bool = False) -> BatchArrays:
        """Tiered-store staging (Config.store_mode): flush the previous
        step's miss write-back (read-your-writes — the next plan's
        cold-fetch must see it), resolve this batch's keys through the
        hot map, fetch miss rows from the host cold store, and ship
        refs + miss blocks.  The plan stays armed on the store until
        dispatch_train pairs it with the step's miss output."""
        from xflow_tpu.io.compact import CompactBatch

        if isinstance(batch, CompactBatch):
            batch = batch.expand()
        store = self.store
        store.complete_pending()
        wire, plan = store.plan_batch(
            batch, obs=self.obs, param_only=predict
        )
        self._book_wire(
            sum(int(v.nbytes) for v in wire.values())
            + plan.miss_nbytes,
            batch.num_real(),
        )
        from xflow_tpu.parallel.mesh import replicated

        # one direct host->device transfer per plane (a jnp.asarray
        # hop first would commit to the default device and pay a
        # second device-to-device reshard — on a path where the
        # staging ring is pinned off, that cost is fully serial)
        arrays = {
            k: jax.device_put(v, self._bsharding)
            for k, v in wire.items()
        }
        rep = replicated(self.mesh)
        arrays["miss"] = {
            tname: {
                aname: jax.device_put(a, rep)
                for aname, a in arrs.items()
            }
            for tname, arrs in plan.miss_rows.items()
        }
        store.stage(arrays, plan)
        return arrays

    def _put_batch_impl(self, batch, predict: bool = False) -> BatchArrays:
        if self.store is not None:
            return self._put_batch_tiered(batch, predict=predict)
        wire, cb = self.host_wire_np(
            # one-way idempotent latch: racing transfer-ahead workers
            # can at worst BOTH run the first-batch validation — extra
            # checking, never missed checking (xf: ignore[XF008])
            batch, check=not self._compact_validated
        )
        self._compact_validated = True  # same latch; xf: ignore[XF008]
        self._book_wire(
            sum(int(v.nbytes) for v in wire.values()),
            batch.num_real(),
            cb=cb,
        )
        arrays = {k: jnp.asarray(v) for k, v in wire.items()}
        if jax.process_count() > 1:
            # Each host loaded its own shard subset (trainer._my_shards);
            # assemble a global array from per-process local batches.
            from jax.experimental import multihost_utils

            return {
                k: multihost_utils.host_local_array_to_global_array(
                    v, self.mesh, self._bsharding.spec
                )
                for k, v in arrays.items()
            }
        return {
            k: jax.device_put(v, self._bsharding) for k, v in arrays.items()
        }

    def dispatch_train(
        self, state: State, arrays: BatchArrays
    ) -> tuple[State, dict[str, jax.Array]]:
        """The jitted train call under the 'dispatch' phase.  Dispatch
        returns as soon as XLA enqueues the program; time the device
        spends actually computing surfaces later as 'device_block' (the
        epoch-end metrics fetch) — the dispatch/block split is what
        tells an input-bound run from a compute-bound one."""
        with self.obs.phase("dispatch"):
            if self.store is not None:
                return self._dispatch_tiered(state, arrays)
            return self.train(state, arrays)

    def _dispatch_tiered(
        self, state: State, arrays: BatchArrays
    ) -> tuple[State, dict[str, jax.Array]]:
        """Tiered dispatch: pair THESE arrays' staged plan (identity-
        keyed — a foreign arrays dict raises) with the hot+miss jit and
        defer the miss write-back (completed before the next plan —
        store/tiered.py ordering)."""
        plan = self.store.take_staged(arrays)
        new_state, miss_out, metrics = self.store.hot.train(state, arrays)
        self.store.defer_complete(plan, miss_out)
        return new_state, metrics

    def _expand_dict_wire(self, w: BatchArrays) -> BatchArrays:
        """Inverse of CompactBatch.wire (io/compact.py), inside the
        jitted step: rebuild the padded [B, K] planes from the flat
        tiered streams, and keep the host-computed dictionary indices
        as ``cold_uidx``/``cold_dict_keys``/``cold_tail_keys`` so
        _scatter_grads can consolidate WITHOUT a device argsort.

        Every plane capacity is static (plane_cap bucketing), so one
        steady batch geometry is one compiled program; the per-batch
        real counts arrive as the cc/hc count planes and the cw_cun
        scalar."""
        cfg = self.cfg
        kc = cfg.max_nnz
        b = w["cw_cc"].shape[0]
        t_sent = jnp.int32(cfg.table_size)

        def bits(plane: jax.Array, n: int) -> jax.Array:
            i = jnp.arange(n, dtype=jnp.int32)
            return (
                plane[i >> 3].astype(jnp.int32) >> (i & 7)
            ) & 1

        def keys_plane(plane: jax.Array) -> jax.Array:
            if plane.ndim == 1:  # u32
                return plane.astype(jnp.int32)
            p = plane.astype(jnp.int32)  # [n, 3] u24 little-endian
            return p[:, 0] | (p[:, 1] << 8) | (p[:, 2] << 16)

        def tiered(
            counts, flags_plane, a_plane, b_vals, width
        ):
            """Rebuild a [B, width] id plane from two flat tier streams:
            per-entry flag bit 1 -> stream ``a_plane``, 0 -> ``b_vals``
            (already decoded [capB] i32).  Returns (ids2d, valid,
            a_pos2d, is_a, is_b, entry2d) for consumers that also need
            the tier ranks (the cold consolidation)."""
            rp = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)]
            )
            colj = jnp.arange(width, dtype=jnp.int32)[None, :]
            entry = rp[:-1, None] + colj
            valid = colj < counts[:, None]
            cap = flags_plane.shape[0] * 8
            e = jnp.clip(entry, 0, max(cap - 1, 0))
            if cap == 0:
                z = jnp.zeros((b, width), jnp.int32)
                return z, valid, z, z > 0, z > 0
            f = bits(flags_plane, cap)
            a_pos = jnp.cumsum(f) - 1
            b_pos = jnp.cumsum(1 - f) - 1
            fe = f[e]
            cap_a = a_plane.shape[0]
            cap_b = b_vals.shape[0]
            av = (
                a_plane[jnp.clip(a_pos[e], 0, cap_a - 1)].astype(
                    jnp.int32
                )
                if cap_a
                else jnp.zeros((b, width), jnp.int32)
            )
            bv = (
                b_vals[jnp.clip(b_pos[e], 0, cap_b - 1)]
                if cap_b
                else jnp.zeros((b, width), jnp.int32)
            )
            is_a = valid & (fe == 1)
            is_b = valid & (fe == 0)
            ids = jnp.where(is_a, av, jnp.where(is_b, bv, 0))
            return ids, valid, av, is_a, is_b

        def flat_slots(plane, counts, width):
            rp = jnp.concatenate(
                [jnp.zeros(1, jnp.int32), jnp.cumsum(counts)]
            )
            colj = jnp.arange(width, dtype=jnp.int32)[None, :]
            valid = colj < counts[:, None]
            cap = plane.shape[0]
            if cap == 0:
                return jnp.zeros((b, width), jnp.int32)
            e = jnp.clip(rp[:-1, None] + colj, 0, cap - 1)
            return jnp.where(valid, plane[e].astype(jnp.int32), 0)

        cc = w["cw_cc"].astype(jnp.int32)
        tail_keys = keys_plane(w["cw_ct"])
        # cold: tier A = dictionary indices (resolved through cw_cu),
        # tier B = raw tail keys
        di2d, cvalid, di_raw, is_dict, is_tail = tiered(
            cc, w["cw_cf"], w["cw_ci"], tail_keys, kc
        )
        cu = keys_plane(w["cw_cu"])
        cap_d = cu.shape[0]
        nd = w["cw_cun"][0]
        if cap_d:
            dict_key2d = cu[jnp.clip(di_raw, 0, cap_d - 1)]
            keys2d = jnp.where(
                is_dict, dict_key2d, jnp.where(is_tail, di2d, 0)
            )
            dict_keys_eff = jnp.where(
                jnp.arange(cap_d) < nd, cu, t_sent
            )
        else:
            keys2d = jnp.where(is_tail, di2d, 0)
            dict_keys_eff = cu
        cmask = cvalid.astype(jnp.float32)
        out = {
            "keys": keys2d,
            "slots": (
                flat_slots(w["cw_cs"], cc, kc)
                if "cw_cs" in w
                else jnp.zeros_like(keys2d)
            ),
            "vals": cmask,
            "mask": cmask,
            "labels": bits(w["cw_lb"], b).astype(jnp.float32),
            "weights": bits(w["cw_wb"], b).astype(jnp.float32),
            # the host-computed consolidation plan (Config.wire_dedup):
            # occurrence -> dictionary slot (cap_d = dump for padding
            # and tail), tail occurrences sentinel-coded for a direct
            # drop-mode scatter, dictionary slot -> table row
            "cold_uidx": jnp.where(is_dict, di_raw, cap_d),
            "cold_tail_keys": jnp.where(is_tail, di2d, t_sent),
            "cold_dict_keys": dict_keys_eff,
        }
        if "cw_hc" in w:
            kh = cfg.hot_nnz
            hc = w["cw_hc"].astype(jnp.int32)
            if w["cw_hxh"].shape[0]:  # u12 tier: u8 lows + nibble highs
                hib = w["cw_hxh"].astype(jnp.int32)
                hi = jnp.stack(
                    [hib & 0xF, hib >> 4], axis=1
                ).reshape(-1)[: w["cw_hx"].shape[0]]
                hx_vals = w["cw_hx"].astype(jnp.int32) | (hi << 8)
            else:
                hx_vals = w["cw_hx"].astype(jnp.int32)
            hot2d, hvalid, _, _, _ = tiered(
                hc, w["cw_hf"], w["cw_h8"], hx_vals, kh
            )
            hmask = hvalid.astype(jnp.float32)
            out["hot_keys"] = hot2d
            out["hot_slots"] = (
                flat_slots(w["cw_hs"], hc, kh)
                if "cw_hs" in w
                else jnp.zeros_like(hot2d)
            )
            out["hot_vals"] = hmask
            out["hot_mask"] = hmask
        return out

    def _expand_wire(self, batch: BatchArrays) -> BatchArrays:
        """Inverse of batch_to_compact, inside the jitted step: padding
        is key == -1; real entries have val = mask = 1 (hash mode);
        slots widen from the u8 plane when the model reads them, else
        reconstruct as zeros.  Dictionary-wire batches (cw_* planes,
        Config.wire_dedup) decode through _expand_dict_wire instead."""
        if "cw_cc" in batch:
            return self._expand_dict_wire(batch)
        if "ckeys" not in batch:
            return batch
        ckeys = batch["ckeys"]
        mask = (ckeys >= 0).astype(jnp.float32)
        out = {
            "keys": jnp.maximum(ckeys, 0),
            "slots": (
                batch["slots_u8"].astype(jnp.int32)
                if "slots_u8" in batch
                else jnp.zeros_like(ckeys)
            ),
            "vals": mask,
            "mask": mask,
            "labels": batch["labels_u8"].astype(jnp.float32),
            "weights": batch["weights_u8"].astype(jnp.float32),
        }
        if "hot_ckeys_u16" in batch:
            # u16 plane: 0xFFFF is the pad sentinel (compact_wire_np;
            # legal only for H <= 2^15, where ids cannot reach it) —
            # normalize to the int32 -1 convention and share the tail
            h16 = batch["hot_ckeys_u16"].astype(jnp.int32)
            hot = jnp.where(h16 == 0xFFFF, -1, h16)
        elif "hot_ckeys" in batch:
            hot = batch["hot_ckeys"]
        else:
            hot = None
        if hot is not None:
            hmask = (hot >= 0).astype(jnp.float32)
            out["hot_keys"] = jnp.maximum(hot, 0)
            out["hot_slots"] = (
                batch["hot_slots_u8"].astype(jnp.int32)
                if "hot_slots_u8" in batch
                else jnp.zeros_like(hot)
            )
            out["hot_vals"] = hmask
            out["hot_mask"] = hmask
        return out

    def _gather_model_rows(
        self, tables: dict[str, dict[str, jax.Array]], batch: BatchArrays
    ) -> dict[str, jax.Array]:
        # Forward gather uses raw keys; padding entries read row 0 but are
        # masked out of every reduction by batch["mask"].
        cold = {name: t["param"][batch["keys"]] for name, t in tables.items()}
        if "hot_keys" not in batch:
            return cold
        # Hot section: two-level one-hot MXU gather over table rows
        # [0, H) (ops/hot.py); rows for the two sections are concatenated
        # feature-axis-first so the model sees one [B, Kh+Kc, D] block
        # aligned with _model_view's concatenated slots/vals/mask.
        from xflow_tpu.ops.hot import hot_gather

        h = self.cfg.hot_size
        b, kh = batch["hot_keys"].shape
        out = {}
        for name, t in tables.items():
            d = t["param"].shape[-1]
            if self._mxu_hot[name]:
                hot = hot_gather(
                    t["param"][:h],
                    batch["hot_keys"].reshape(-1),
                    dtype=self._hot_dtype,
                    impl=self._hot_impl,
                ).reshape(b, kh, d)
            else:
                # opted-out table (TableSpec.hot=False): hot rows are
                # ordinary table rows [0, H) — plain gather; padding
                # reads row 0, masked downstream like the cold plane
                hot = t["param"][batch["hot_keys"]]
            out[name] = jnp.concatenate([hot, cold[name]], axis=1)
        return out

    def _model_view(self, batch: BatchArrays) -> BatchArrays:
        """Batch as the model sees it: hot + cold sections concatenated
        along the feature axis (models are permutation-invariant over a
        sample's features — they reduce over the feature axis)."""
        if "hot_keys" not in batch:
            return batch
        view = dict(batch)
        view["keys"] = jnp.concatenate(
            [batch["hot_keys"], batch["keys"]], axis=1
        )
        view["slots"] = jnp.concatenate(
            [batch["hot_slots"], batch["slots"]], axis=1
        )
        view["vals"] = jnp.concatenate(
            [batch["hot_vals"], batch["vals"]], axis=1
        )
        view["mask"] = jnp.concatenate(
            [batch["hot_mask"], batch["mask"]], axis=1
        )
        return view

    # -- compiled bodies ---------------------------------------------------

    def _logit(
        self, rows: dict[str, jax.Array], batch: BatchArrays, dense: dict
    ) -> jax.Array:
        if getattr(self.model, "autodiff", False):
            return self.model.logit(rows, batch, dense)
        return self.model.logit(rows, batch)

    def _forward_grads(
        self,
        tables: dict,
        dense: dict,
        batch: BatchArrays,
        num_real: jax.Array,
    ):
        """pctr + per-occurrence gradients for one (micro)batch.

        Returns (pctr, occ_grads, grad_dense_or_None); occ_grads are
        already residual-scaled and divided by the FULL batch's real
        example count, so accumulating them across microbatch slices
        reproduces the whole-batch mean-gradient semantics exactly
        (lr_worker.cc:116-118)."""
        rows = self._gather_model_rows(tables, batch)
        return self._grads_from_rows(rows, dense, batch, num_real)

    def _grads_from_rows(
        self,
        rows: dict,
        dense: dict,
        batch: BatchArrays,
        num_real: jax.Array,
    ):
        """_forward_grads with the row gather already done — the hot
        sequential inner supplies rows from the carried hot head plus
        a window-start cold pre-gather instead of a live table
        gather.  Delegates to the module-level ``grads_from_rows`` (the
        one forward/backward, shared with store/hot.py)."""
        return grads_from_rows(
            self.model, rows, dense, self._model_view(batch), num_real
        )

    def _hot_keys_eff_dma(self, batch: BatchArrays) -> jax.Array:
        """Hot-plane keys sentinel-coded for a DROP-mode scatter into
        the FULL [T, D] table (opted-out tables, TableSpec.hot=False):
        masked slots → T, out of range.  _hot_keys_eff's sentinel H is
        a real table row and only works for [H, D] buffers."""
        return jnp.where(
            batch["hot_mask"] > 0,
            batch["hot_keys"],
            jnp.int32(self.cfg.table_size),
        ).reshape(-1)

    def _cold_keys_eff(self, batch: BatchArrays) -> jax.Array:
        """Sentinel-coded flat cold keys: masked slots → T, which the
        drop-mode scatters and consolidate_plan treat as out-of-range.
        The ONE definition of the cold sentinel convention (counterpart
        of _hot_keys_eff), shared by _scatter_grads, _sparse_update and
        the hot inner's window-end pass."""
        return jnp.where(
            batch["mask"] > 0, batch["keys"], jnp.int32(self.cfg.table_size)
        ).reshape(-1)

    def _cold_accumulate(
        self, gbuf: jax.Array, keys_eff: jax.Array, occ: jax.Array, plan
    ) -> jax.Array:
        """Accumulate per-occurrence cold grads [M, D] into a [T, D]
        buffer under the ONE sentinel/drop convention (pad keys carry
        index T, dropped by mode='drop'), via the consolidate plan
        when one is supplied.  Shared by _scatter_grads and the hot
        inner's window-end pass so the two cannot drift."""
        if plan is not None:
            order, seg, ukeys = plan
            gsum = consolidate_apply(occ, order, seg)
            return gbuf.at[ukeys].add(gsum, mode="drop")
        return gbuf.at[keys_eff].add(occ, mode="drop")

    def _scatter_grads(
        self,
        tables: dict,
        batch: BatchArrays,
        occ_grads: dict,
        gbufs: dict,
        dict_plan: dict | None = None,
    ) -> dict:
        """Accumulate per-occurrence grads into dense [T, D] buffers
        (one per table): scatter-add for the cold section, two-level
        one-hot MXU matmuls for the hot section (ops/hot.py).

        With ``dict_plan`` (the dict wire's host-computed dictionary,
        Config.wire_dedup + cold_consolidate) the duplicated cold HEAD
        consolidates by segment-sum over the shipped u16 indices — U
        unique big-table slices instead of one per occurrence, and no
        device argsort — while the near-unique tail keeps the direct
        drop-mode scatter (consolidating it would cost more than it
        collapses; io/compact.py)."""
        cfg = self.cfg
        kh = batch["hot_keys"].shape[1] if "hot_keys" in batch else 0
        use_dict = (
            dict_plan is not None
            and "cold_uidx" in dict_plan
            and cfg.cold_consolidate
        )
        plan = None
        if use_dict:
            uidx = dict_plan["cold_uidx"].reshape(-1)
            tail_eff = dict_plan["cold_tail_keys"].reshape(-1)
            dict_keys_eff = dict_plan["cold_dict_keys"]
            cap_d = dict_keys_eff.shape[0]
            keys_eff = None
        else:
            keys_eff = self._cold_keys_eff(batch)
            if cfg.cold_consolidate:
                # one shared argsort over the cold keys; every table's
                # gradients ride the same permutation/segments
                plan = consolidate_plan(keys_eff, cfg.table_size)
        if kh:
            from xflow_tpu.ops.hot import hot_scatter

            hot_keys_eff = self._hot_keys_eff(batch)
        out = {}
        for name, table in tables.items():
            d = table["param"].shape[-1]
            occ = occ_grads[name]
            if kh:
                # hot section grads ride the MXU into a dense [H, D]
                # buffer; cold grads keep the DMA scatter path.
                hot_g = occ[:, :kh].reshape(-1, d)
                occ = occ[:, kh:]
            if use_dict:
                occ_flat = occ.reshape(-1, d)
                gsum = consolidate_indexed(occ_flat, uidx, cap_d)
                gbuf = gbufs[name].at[dict_keys_eff].add(
                    gsum, mode="drop"
                )
                gbuf = gbuf.at[tail_eff].add(occ_flat, mode="drop")
            else:
                gbuf = self._cold_accumulate(
                    gbufs[name], keys_eff, occ.reshape(-1, d), plan
                )
            if kh:
                if self._mxu_hot[name]:
                    ghot = hot_scatter(
                        hot_keys_eff, hot_g, cfg.hot_size,
                        dtype=self._hot_dtype, impl=self._hot_impl,
                    )
                    gbuf = gbuf.at[: cfg.hot_size].add(ghot)
                else:
                    gbuf = gbuf.at[self._hot_keys_eff_dma(batch)].add(
                        hot_g, mode="drop"
                    )
            out[name] = gbuf
        return out

    def _train_impl(
        self, state: State, batch: BatchArrays
    ) -> tuple[State, dict[str, jax.Array]]:
        cfg = self.cfg
        batch = self._expand_wire(batch)
        # The dict wire's host consolidation plan has no batch leading
        # axis, so it cannot ride _interleaved_slices; only the plain
        # dense whole-batch scatter consumes it (via _scatter_grads) —
        # every other path trains on the reconstructed key planes.
        dict_plan = {
            k: batch.pop(k)
            for k in ("cold_uidx", "cold_tail_keys", "cold_dict_keys")
            if k in batch
        }
        if cfg.update_mode == "sequential" and cfg.microbatch > 1:
            return self._train_sequential(state, batch)

        tables = state["tables"]
        dense = state["dense"]
        num_real = jnp.maximum(jnp.sum(batch["weights"]), 1.0)

        # sequential with one slice degenerates to a single whole-batch
        # update; honor the configured inner so a sparse-inner run at
        # microbatch=1 doesn't silently pay a full-table dense pass.
        # The 'hot' inner deliberately does NOT route here: with one
        # slice its dispatch window IS the whole batch (per-slice head
        # update + window-end tail collapse into one whole-batch
        # update), so it falls through to the dense accumulate path
        # below — the explicit degenerate form, equivalence pinned by
        # tests/test_sequential.py::test_sequential_microbatch_one_is_dense.
        if cfg.update_mode == "sparse" or (
            cfg.update_mode == "sequential"
            and cfg.sequential_inner == "sparse"
        ):
            pctr, occ_grads, grad_dense = self._forward_grads(
                tables, dense, batch, num_real
            )
            # hot planes, when present, take _sparse_update's hybrid
            # path (dense [H, D] head update, overflow fold)
            new_tables = self._sparse_update(tables, batch, occ_grads)
            ll = logloss(batch["labels"], pctr, batch["weights"])
            cnt = jnp.sum(batch["weights"])
            return self._finish_step(
                state, new_tables, dense, grad_dense, ll, cnt
            )

        # -- dense mode: accumulate grads into per-table buffers, then
        # ONE optimizer pass.  Scatter-add consolidates duplicate keys;
        # the recurrence runs elementwise over the full table — no sort,
        # no row gather/scatter.  Untouched rows see g=0, for which
        # FTRL/SGD are idempotent (optim docstrings).
        gbufs = {
            # the [T, D] buffer IS dense mode's design (small-table
            # form; 'sparse' is the 2^28 form) — budgeted in
            # memory-budget.json, justified here (xf: ignore[XF010])
            name: jnp.zeros_like(t["param"]) for name, t in tables.items()
        }
        s = cfg.microbatch
        if s == 1:
            pctr, occ_grads, grad_dense = self._forward_grads(
                tables, dense, batch, num_real
            )
            gbufs = self._scatter_grads(
                tables, batch, occ_grads, gbufs, dict_plan=dict_plan
            )
            ll = logloss(batch["labels"], pctr, batch["weights"])
            cnt = jnp.sum(batch["weights"])
        else:
            # Gradient accumulation (Config.microbatch): scan over batch
            # slices so every [B-slice, nnz, D] intermediate is 1/s the
            # size.  Grads are pre-divided by the FULL batch num_real, so
            # the accumulated buffers equal the single-pass ones.
            xs = _interleaved_slices(batch, s)
            gdense0 = jax.tree.map(jnp.zeros_like, dense)

            def body(carry, bslice):
                gbufs_c, gdense_c, nll_c, cnt_c = carry
                pctr_s, occ_s, gd = self._forward_grads(
                    tables, dense, bslice, num_real
                )
                gbufs_c = self._scatter_grads(
                    tables, bslice, occ_s, gbufs_c
                )
                if gd is not None:
                    gdense_c = jax.tree.map(
                        lambda a, b: a + b, gdense_c, gd
                    )
                w = bslice["weights"]
                nll_c = nll_c + logloss_sum(bslice["labels"], pctr_s, w)
                return (gbufs_c, gdense_c, nll_c, cnt_c + jnp.sum(w)), None

            zero = jnp.zeros((), jnp.float32)
            (gbufs, grad_dense, nll_sum, cnt), _ = jax.lax.scan(
                body, (gbufs, gdense0, zero, zero), xs
            )
            if not dense:
                grad_dense = None
            ll = nll_sum / jnp.maximum(cnt, 1.0)

        new_tables = {
            name: self.optimizer.update_rows(table, gbufs[name])
            for name, table in tables.items()
        }
        return self._finish_step(
            state, new_tables, dense, grad_dense, ll, cnt
        )

    def _hot_keys_eff(self, batch: BatchArrays) -> jax.Array:
        """Sentinel-coded flat hot keys: masked slots → H, which both
        the dense path's hot_scatter and the hybrid's [H, D] fold drop
        as out-of-range.  The ONE definition of the hot sentinel
        convention, shared by _scatter_grads and _sparse_update so the
        dense and hybrid update paths cannot drift."""
        return jnp.where(
            batch["hot_mask"] > 0,
            batch["hot_keys"],
            jnp.int32(self.cfg.hot_size),
        ).reshape(-1)

    def _apply_touched_rows(
        self, table: dict, ukeys: jax.Array, gsum: jax.Array
    ) -> dict:
        """Gather state rows at the consolidated unique keys, run the
        optimizer recurrence, scatter the new rows back (sentinel keys
        clamp on gather and drop on scatter — ops/sparse.py).  The ONE
        touched-rows application, shared by _sparse_update (both the
        MXU and opted-out variants) and the hot inner's sparse
        window-end so the three cannot drift."""
        state_rows = {k: gather_rows(arr, ukeys) for k, arr in table.items()}
        new_rows = self.optimizer.update_rows(state_rows, gsum)
        return {
            k: scatter_rows(table[k], ukeys, new_rows[k]) for k in table
        }

    def _sparse_update(
        self, tables: dict, batch: BatchArrays, occ_grads: dict
    ) -> dict:
        """Touched-rows-only optimizer application (the reference's
        Push path, ftrl.h:54-79): consolidate per unique key, gather
        state rows, run the recurrence, scatter back.  Shared by the
        sparse update mode (whole batch) and sequential mode's sparse
        inner (per slice — the only viable per-slice form at
        north-star table sizes).

        With the hot table on, this becomes a HYBRID: cold keys keep
        the touched-rows path while the hot section's gradients ride
        the MXU into a dense [H, D] buffer whose rows get one dense
        optimizer pass (H rows ≈ 115 KB of traffic — negligible next
        to a [T, D] full-table pass).  Exactly-once semantics: hot
        rows can ALSO appear among the cold keys (split_hot overflow
        spill, io/batch.py:89-93), so cold contributions to rows
        < H are folded into the hot gradient buffer and masked out of
        the sparse scatter — every row sees ONE summed-gradient
        update, matching the dense path's gbuf semantics bit-for-bit
        in structure.

        Tables opted OUT of the MXU path (TableSpec.hot=False, e.g.
        FFM's wide v) instead fold their hot-plane occurrences into a
        SECOND consolidate over cold+hot keys and take the plain
        touched-rows update for everything — same exactly-once
        guarantee, no [H, D] buffer."""
        cfg = self.cfg
        kh = batch["hot_keys"].shape[1] if "hot_keys" in batch else 0
        sentinel = jnp.int32(cfg.table_size)
        keys_eff = self._cold_keys_eff(batch)
        # one shared argsort; every table's gradients ride the same
        # permutation/segments (same sharing as _scatter_grads)
        order, seg, ukeys = consolidate_plan(keys_eff, cfg.table_size)
        plan_all = None
        if kh:
            from xflow_tpu.ops.hot import hot_scatter

            hsize = cfg.hot_size
            hot_keys_eff = self._hot_keys_eff(batch)
            in_hot = ukeys < hsize
            ukeys_cold = jnp.where(in_hot, sentinel, ukeys)
            # consolidated cold sums destined for hot rows; index H
            # (out of range for the [H, D] buffer) drops the rest
            ukeys_hotpart = jnp.where(in_hot, ukeys, jnp.int32(hsize))
            if not all(self._mxu_hot.values()):
                # opted-out tables: one combined plan over cold+hot
                # occurrence keys (shared by every such table)
                keys_all = jnp.concatenate(
                    [keys_eff, self._hot_keys_eff_dma(batch)]
                )
                plan_all = consolidate_plan(keys_all, cfg.table_size)
        else:
            ukeys_cold = ukeys
        new_tables = {}
        for name, table in tables.items():
            d = table["param"].shape[-1]
            occ = occ_grads[name]
            if kh:
                hot_g = occ[:, :kh].reshape(-1, d)
                occ = occ[:, kh:]
            if kh and not self._mxu_hot[name]:
                order_a, seg_a, ukeys_a = plan_all
                gsum_a = consolidate_apply(
                    jnp.concatenate([occ.reshape(-1, d), hot_g]),
                    order_a,
                    seg_a,
                )
                new_tables[name] = self._apply_touched_rows(
                    table, ukeys_a, gsum_a
                )
                continue
            gsum = consolidate_apply(occ.reshape(-1, d), order, seg)
            new = self._apply_touched_rows(table, ukeys_cold, gsum)
            if kh:
                ghot = hot_scatter(
                    hot_keys_eff, hot_g, hsize,
                    dtype=self._hot_dtype, impl=self._hot_impl,
                )
                # non-hot slots carry index H -> dropped; no mask needed
                ghot = ghot.at[ukeys_hotpart].add(gsum, mode="drop")
                hot_rows = {k: arr[:hsize] for k, arr in new.items()}
                new_hot = self.optimizer.update_rows(hot_rows, ghot)
                new = {
                    k: jax.lax.dynamic_update_slice_in_dim(
                        new[k], new_hot[k], 0, axis=0
                    )
                    for k in new
                }
            new_tables[name] = new
        return new_tables

    def _train_sequential(
        self, state: State, batch: BatchArrays
    ) -> tuple[State, dict[str, jax.Array]]:
        """update_mode='sequential': scan over microbatch slices with
        the TABLES in the scan carry — the optimizer recurrence runs
        once per slice, with gradients divided by the SLICE's real
        count, and slice k reads the tables as slice k-1 left them.
        One dispatch of batch_size examples is therefore step-for-step
        the same training as `microbatch` successive dense steps of
        batch_size/microbatch examples (tests/test_sequential.py
        asserts bitwise-close equality).  This is what composes the
        proven small-batch FTRL convergence (docs/CONVERGENCE.md,
        B=512) with device-rate dispatch: the reference's effective
        optimizer batch is a per-thread text-block slice of a few
        hundred rows (lr_worker.cc:116-118,190-196), which a
        throughput-sized B would otherwise dilute ~256×.

        Cost model (dense inner, the default): each slice pays one
        full-table elementwise optimizer pass (streaming ~7 arrays of
        [T, D] HBM traffic), so wall-clock per example grows with
        microbatch × table bytes / batch.  With
        config.sequential_inner='sparse' the slice instead pays an
        O(slice nnz) consolidate + gather/update/scatter of touched
        rows only — table-size-independent, the form 2^28-scale tables
        require.  See docs/PERF.md 'Sequential mode'."""
        cfg = self.cfg
        if cfg.sequential_inner == "hot":
            return self._train_sequential_hot(state, batch)
        tables = state["tables"]
        dense = state["dense"]
        s = cfg.microbatch
        xs = _interleaved_slices(batch, s)

        def body(carry, bslice):
            tables_c, dense_c, nll_c, cnt_c = carry
            w_sum = jnp.sum(bslice["weights"])
            num_real = jnp.maximum(w_sum, 1.0)
            pctr_s, occ_s, gd = self._forward_grads(
                tables_c, dense_c, bslice, num_real
            )
            if cfg.sequential_inner == "sparse":
                # touched-rows-only per slice: O(slice nnz), the only
                # viable inner at T=2^28 (config.sequential_inner)
                new_tables = self._sparse_update(tables_c, bslice, occ_s)
            else:
                gbufs = {
                    # dense inner: full-table pass per slice BY CHOICE
                    # (config.sequential_inner documents the cost; the
                    # sparse/hot inners are the 2^28 forms) — budgeted
                    # in memory-budget.json (xf: ignore[XF010])
                    name: jnp.zeros_like(t["param"])
                    for name, t in tables_c.items()
                }
                gbufs = self._scatter_grads(
                    tables_c, bslice, occ_s, gbufs
                )
                new_tables = {
                    name: self.optimizer.update_rows(table, gbufs[name])
                    for name, table in tables_c.items()
                }
            new_dense = self._apply_dense_sgd(dense_c, gd)
            nll_c = nll_c + logloss_sum(
                bslice["labels"], pctr_s, bslice["weights"]
            )
            return (new_tables, new_dense, nll_c, cnt_c + w_sum), None

        zero = jnp.zeros((), jnp.float32)
        (new_tables, new_dense, nll_sum, cnt), _ = jax.lax.scan(
            body, (tables, dense, zero, zero), xs
        )
        ll = nll_sum / jnp.maximum(cnt, 1.0)
        return {
            "tables": new_tables,
            "dense": new_dense,
            "step": state["step"] + 1,
        }, {"logloss": ll, "count": cnt}

    def _train_sequential_hot(
        self, state: State, batch: BatchArrays
    ) -> tuple[State, dict[str, jax.Array]]:
        """sequential_inner='hot': hot-FINE / cold-COARSE.

        The dense and sparse inners both pay per-slice work that fights
        the hardware — a full [T, D] HBM stream (dense) or a
        latency-bound consolidate+gather+scatter of ~85-107 ns/slice
        DMA descriptors (sparse; docs/PERF.md "Multi-lane
        scatter-add").  Measured on v5e they cost 36.8 s and ~50 s per
        10 M-example epoch respectively at the flagship geometry.  This
        inner removes BOTH costs from the scan body:

        * the frequency-hot head (table rows [0, H), ~71% of occurrence
          mass at the lr flagship remap — docs/PERF.md) rides the scan
          carry and takes a FULL-granularity optimizer step per
          B_eff-slice, all in MXU one-hot matmuls + [H, D] elementwise
          work — no DMA;
        * cold (tail) rows are pre-gathered ONCE per dispatch window in
          a single batched DMA gather (the 3 M ex/s throughput path's
          access pattern), their per-occurrence gradients are stacked
          as scan outputs, and the window closes with ONE batched
          scatter-add + ONE full-table optimizer pass — exactly the
          dense-mode tail, amortized over `microbatch` slices.

        Semantics vs true sequential: cold values are stale by at most
        one dispatch window, and a cold key occurring k>1 times in the
        window sees one summed-gradient update instead of k — the
        async-parameter-server behavior of the reference itself, whose
        workers compute on weights pulled a minibatch ago and push
        asynchronously (lr_worker.cc:95-143), here confined to the
        zipf TAIL.  Hot rows — where intra-window repetition actually
        concentrates — get bit-exact B_eff-granular treatment.
        Overflow spill (hot-eligible keys in the cold plane,
        io/batch.py split_hot) is handled exactly once: its grads ride
        the window-end pass, which runs AFTER the evolved head is
        written back, so no update is lost or doubled.  Quality:
        docs/CONVERGENCE.md overlay; wall-clock: docs/PERF.md."""
        cfg = self.cfg
        if "hot_keys" not in batch:
            raise ValueError(
                "sequential_inner='hot' needs hot batch planes — was "
                "the loader built with the hot table geometry?"
            )
        from xflow_tpu.ops.hot import hot_gather, hot_scatter

        tables = state["tables"]
        dense = state["dense"]
        s = cfg.microbatch
        h = cfg.hot_size
        # Window-start cold values: ONE batched gather per table,
        # hoisted out of the scan.  Padding slots read row 0 and are
        # masked out of every reduction downstream (same convention as
        # _gather_model_rows).
        cold_rows = {
            name: t["param"][batch["keys"]] for name, t in tables.items()
        }
        heads0 = {
            name: {k: arr[:h] for k, arr in t.items()}
            for name, t in tables.items()
        }
        xs = (
            _interleaved_slices(batch, s),
            _interleaved_slices(cold_rows, s),
        )

        def body(carry, slice_in):
            heads, dense_c, nll_c, cnt_c = carry
            bslice, cold_slice = slice_in
            w_sum = jnp.sum(bslice["weights"])
            num_real = jnp.maximum(w_sum, 1.0)
            b, kh = bslice["hot_keys"].shape
            rows = {}
            for name, head in heads.items():
                d = head["param"].shape[-1]
                hot = hot_gather(
                    head["param"],
                    bslice["hot_keys"].reshape(-1),
                    dtype=self._hot_dtype,
                    impl=self._hot_impl,
                ).reshape(b, kh, d)
                rows[name] = jnp.concatenate(
                    [hot, cold_slice[name]], axis=1
                )
            pctr_s, occ_s, gd = self._grads_from_rows(
                rows, dense_c, bslice, num_real
            )
            hot_keys_eff = self._hot_keys_eff(bslice)
            new_heads = {}
            cold_occ = {}
            for name, head in heads.items():
                d = head["param"].shape[-1]
                g = occ_s[name]
                hot_g = g[:, :kh].reshape(-1, d)
                cold_occ[name] = g[:, kh:]
                ghot = hot_scatter(
                    hot_keys_eff, hot_g, h,
                    dtype=self._hot_dtype, impl=self._hot_impl,
                )
                new_heads[name] = self.optimizer.update_rows(head, ghot)
            new_dense = self._apply_dense_sgd(dense_c, gd)
            nll_c = nll_c + logloss_sum(
                bslice["labels"], pctr_s, bslice["weights"]
            )
            return (
                (new_heads, new_dense, nll_c, cnt_c + w_sum),
                cold_occ,
            )

        zero = jnp.zeros((), jnp.float32)
        (new_heads, new_dense, nll_sum, cnt), cold_occ = jax.lax.scan(
            body, (heads0, dense, zero, zero), xs
        )
        # Close the window: write the evolved head back, then apply the
        # accumulated cold-tail grads — as ONE dense full-table pass
        # (g=0 rows are idempotent under FTRL/SGD — optim docstrings),
        # or, with Config.hot_windowend='sparse' (auto at
        # table_size_log2 >= 24), through the consolidated touched-rows
        # update: O(window nnz) transients instead of a [T, D] buffer +
        # full-table pass per table — the only viable form at T=2^28
        # (ADVICE step.py:945; analysis rules XF010/XF014).  Either
        # way, spill grads (cold-plane keys < H) land on the
        # written-back head rows here, exactly once.
        keys_eff = self._cold_keys_eff(batch)
        plan = (
            consolidate_plan(keys_eff, cfg.table_size)
            if self._windowend == "sparse" or cfg.cold_consolidate
            else None
        )
        new_tables = {}
        for name, table in tables.items():
            d = table["param"].shape[-1]
            merged = {
                k: jax.lax.dynamic_update_slice_in_dim(
                    table[k], new_heads[name][k], 0, axis=0
                )
                for k in table
            }
            # un-interleave the stacked [s, B/s, Kc, D] slice outputs
            # back to batch order (example i lives at slice i%s,
            # position i//s — _interleaved_slices)
            occ = cold_occ[name].swapaxes(0, 1).reshape(-1, d)
            if self._windowend == "sparse":
                # routed window-end: every table's gradients ride the
                # one shared plan; touched rows see the same summed
                # window gradient the dense pass would apply, pad/
                # sentinel slots gather-clip and scatter-drop
                # (ops/sparse.py module docstring;
                # tests/test_sequential.py equivalence)
                order, seg, ukeys = plan
                gsum = consolidate_apply(occ, order, seg)
                new_tables[name] = self._apply_touched_rows(
                    merged, ukeys, gsum
                )
                continue
            gbuf = self._cold_accumulate(
                # dense window-end (the small-table form; see the
                # routed branch above for 2^28) — budgeted in
                # memory-budget.json (xf: ignore[XF010])
                jnp.zeros_like(table["param"]),
                keys_eff,
                occ,
                plan,
            )
            new_tables[name] = self.optimizer.update_rows(merged, gbuf)
        ll = nll_sum / jnp.maximum(cnt, 1.0)
        return {
            "tables": new_tables,
            "dense": new_dense,
            "step": state["step"] + 1,
        }, {"logloss": ll, "count": cnt}

    def _apply_dense_sgd(self, dense: dict, grad_dense) -> dict:
        """Module-level ``apply_dense_sgd`` bound to this config —
        shared by _finish_step (per-dispatch application) and
        _train_sequential (per-slice application), so the update modes
        cannot drift apart."""
        return apply_dense_sgd(dense, grad_dense, self.cfg.sgd_lr)

    def _finish_step(self, state, new_tables, dense, grad_dense, ll, cnt):
        """Shared step tail for the non-sequential update modes."""
        new_dense = self._apply_dense_sgd(dense, grad_dense)
        metrics = {"logloss": ll, "count": cnt}
        return {
            "tables": new_tables,
            "dense": new_dense,
            "step": state["step"] + 1,
        }, metrics

    def _predict_impl(self, state: State, batch: BatchArrays) -> jax.Array:
        """pctr per example (reference calculate_pctr, lr_worker.cc:46-61)."""
        batch = self._expand_wire(batch)
        for k in ("cold_uidx", "cold_tail_keys", "cold_dict_keys"):
            batch.pop(k, None)  # predict has no scatter to plan for
        rows = self._gather_model_rows(state["tables"], batch)
        return sigmoid_ref(
            self._logit(rows, self._model_view(batch), state["dense"])
        )
