"""Multi-host exchange helpers.

``multihost_utils.process_allgather`` routes values through jax.Arrays,
and without ``jax_enable_x64`` JAX silently canonicalizes int64→int32
and float64→float32 — corrupting byte offsets ≥ 2 GiB and float64
metric accumulators.  ``allgather_exact`` ships the raw bytes as int32
words instead, so any 4-byte-aligned dtype survives bit-exactly.
"""

from __future__ import annotations

import numpy as np


def allgather_exact(arr: np.ndarray) -> np.ndarray:
    """Allgather preserving dtype bit-exactly.

    Returns ``[num_processes, *arr.shape]`` in ``arr``'s dtype.  The
    itemsize must be a multiple of 4 (int32/float32/int64/float64...).
    COLLECTIVE: every process must call with the same shape/dtype.
    """
    from jax.experimental import multihost_utils

    a = np.ascontiguousarray(arr)
    if a.ndim == 0:
        a = a.reshape(1)
    words = a.view(np.int32)
    out = np.asarray(multihost_utils.process_allgather(words))
    return out.view(a.dtype).reshape((-1, *arr.shape))
