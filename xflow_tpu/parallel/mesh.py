"""Device mesh and sharding layout — the ps-lite replacement.

The reference scales two ways (SURVEY §2): data parallelism (each
worker process streams its own shard, lr_worker.cc:210) and parameter
sharding (ps-lite range-partitions the uint64 key space over servers).
On TPU both collapse onto one 1-D mesh axis ``"data"``:

* weight/optimizer tables [T, D] are **row-sharded**: rows split into
  contiguous blocks across devices — the moral equivalent of ps-lite's
  contiguous key-range server partition;
* minibatches are sharded on the batch dimension (data parallelism);
* the cross-device traffic the reference did with ZMQ Push/Pull becomes
  XLA-inserted collectives on the gather/scatter between the data-
  sharded batch and the row-sharded table, riding ICI.

Bootstrap: where the reference needed a scheduler + DMLC_* env vars
(scripts/local.sh:8-19), multi-host here is ``jax.distributed
.initialize()`` + SPMD; single-host multi-device needs nothing.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: int = 0, devices: list | None = None) -> Mesh:
    devs = list(devices) if devices is not None else list(jax.devices())
    if num_devices:
        if num_devices > len(devs):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devs)}"
            )
        devs = devs[:num_devices]
    return Mesh(np.asarray(devs), (DATA_AXIS,))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded, columns replicated."""
    return NamedSharding(mesh, P(DATA_AXIS, None))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Batch dimension sharded."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
