from xflow_tpu.parallel.mesh import make_mesh, table_sharding, batch_sharding
from xflow_tpu.parallel.step import TrainStep, init_state, batch_to_arrays

__all__ = [
    "make_mesh",
    "table_sharding",
    "batch_sharding",
    "TrainStep",
    "init_state",
    "batch_to_arrays",
]
