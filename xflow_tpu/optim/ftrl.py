"""FTRL-proximal, the reference's production optimizer.

Exact recurrence of the server push handler (ftrl.h:58-74), per key k
with incoming gradient g:

    n' = n + g^2
    sigma = (sqrt(n') - sqrt(n)) / alpha
    z' = z + g - sigma * w
    w' = 0                                   if |z'| <= lambda1
       = (sign(z')*lambda1 - z') / ((beta + sqrt(n')) / alpha + lambda2)
                                             otherwise

Pull returns the stored w (ftrl.h:75-76) — in this framework the table
is HBM-resident so "pull" is just the gather in the train step.

Defaults match ftrl.h:17-20: alpha=5e-2, beta=1.0, lambda1=5e-5,
lambda2=10.0.

Latent-factor (v) tables: the reference lazily initializes v entries
with N(0,1)*1e-2 on first touch, server-side inside the optimizer
(ftrl.h:113-120), with n=z=0.  We pre-initialize the whole v table with
the same distribution at state creation instead (models/fm.py,
models/mvm.py).  This is behaviorally equivalent: an untouched row is
never read, and the first push overwrites v from (z, n') exactly as the
reference handler does.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class FTRL:
    alpha: float = 5e-2
    beta: float = 1.0
    lambda1: float = 5e-5
    lambda2: float = 10.0
    name: str = "ftrl"

    def init_aux(self, param: jax.Array) -> dict[str, jax.Array]:
        return {
            "n": jnp.zeros_like(param),
            "z": jnp.zeros_like(param),
        }

    def update_rows(
        self, rows: dict[str, jax.Array], g: jax.Array
    ) -> dict[str, jax.Array]:
        w, n, z = rows["param"], rows["n"], rows["z"]
        n_new = n + g * g
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / self.alpha
        z_new = z + g - sigma * w
        shrink = (jnp.sign(z_new) * self.lambda1 - z_new) / (
            (self.beta + jnp.sqrt(n_new)) / self.alpha + self.lambda2
        )
        w_new = jnp.where(jnp.abs(z_new) <= self.lambda1, 0.0, shrink)
        # Never-touched entries (n' = n + g^2 == 0 iff no gradient has ever
        # arrived) keep their initialization — the lazy server-side init
        # semantics of ftrl.h:113-120, required so the dense update path
        # doesn't wipe random v init table-wide on step 1.  A *touched*
        # entry pushed an exactly-zero gradient (sigmoid clamp) has n > 0
        # and is recomputed from (z, n), matching the reference handler's
        # unconditional recompute (ftrl.h:58-74).
        w_new = jnp.where(n_new == 0.0, w, w_new)
        return {"param": w_new, "n": n_new, "z": z_new}
