from xflow_tpu.optim.base import Optimizer
from xflow_tpu.optim.ftrl import FTRL
from xflow_tpu.optim.sgd import SGD


def make_optimizer(cfg) -> Optimizer:
    if cfg.optimizer == "ftrl":
        return FTRL(
            alpha=cfg.alpha, beta=cfg.beta, lambda1=cfg.lambda1, lambda2=cfg.lambda2
        )
    if cfg.optimizer == "sgd":
        return SGD(lr=cfg.sgd_lr)
    raise ValueError(f"unknown optimizer {cfg.optimizer!r}")


__all__ = ["Optimizer", "FTRL", "SGD", "make_optimizer"]
