"""Optimizer protocol.

Reference optimizers are server-side request-handler functors closing
over per-key state maps (ftrl.h:22-155, sgd.h:18-112).  Here an
optimizer is a pure function over gathered state rows: it declares what
auxiliary state accompanies a parameter table and how a row updates
given the consolidated gradient for its key.  The framework owns
gather/scatter and sharding; the optimizer sees only dense [U, D]
blocks, so the same code runs on any mesh.
"""

from __future__ import annotations

from typing import Protocol

import jax


class Optimizer(Protocol):
    name: str

    def init_aux(self, param: jax.Array) -> dict[str, jax.Array]:
        """Auxiliary state arrays, same shape/sharding as ``param``."""
        ...

    def update_rows(
        self, rows: dict[str, jax.Array], g: jax.Array
    ) -> dict[str, jax.Array]:
        """Pure per-row update.

        ``rows`` maps "param" plus each aux name to [U, D] blocks;
        ``g`` is the consolidated gradient [U, D].  Must be well-defined
        for g=0 (padding) and idempotent there.
        """
        ...
