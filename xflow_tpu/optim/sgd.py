"""Plain SGD (the reference's dormant alternative, sgd.h:18-112).

Push applies ``w -= lr * g`` with lr=0.001 (sgd.h:16,52).  The
reference's pull branch contains a duplicated-inner-loop bug
(sgd.h:53-57, nested ``for j`` inside ``for j``) — fixed here, per the
SURVEY quirks ledger: pull is an identity read.
"""

from __future__ import annotations

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class SGD:
    lr: float = 0.001
    name: str = "sgd"

    def init_aux(self, param: jax.Array) -> dict[str, jax.Array]:
        return {}

    def update_rows(
        self, rows: dict[str, jax.Array], g: jax.Array
    ) -> dict[str, jax.Array]:
        return {"param": rows["param"] - self.lr * g}
